"""Sparse (touched-slot) ingest codecs — the large-n_v wire format.

VERDICT r2 item 2: dense i32[n_v] payloads invert the codec's compression
at Twitter-class n_v (256 MB per chunk at n_v ~ 2^26). The sparse codecs
emit counted (vertex, value) pairs — payload and host combine work
proportional to the chunk's *touched* vertices, mirroring the reference's
per-subtask HashMap partial fold (SummaryBulkAggregation.java:109-130).
These tests assert pair/dense equivalence at the native layer, numpy
fallback parity, end-to-end component/degree parity on single shard and
the 8-virtual-device mesh, and that wire bytes track touched counts.
"""

import numpy as np
import pytest

from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.engine.aggregation import bucket_stack_payloads
from gelly_tpu.library.connected_components import (
    cc_labels_numpy,
    cc_pairs_numpy,
    connected_components,
    labels_to_components,
)
from gelly_tpu.parallel import mesh as mesh_lib
from gelly_tpu.utils import native

N_V = 64


def _rand_edges(n_e=500, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, N_V, n_e).astype(np.int64),
            rng.integers(0, N_V, n_e).astype(np.int64))


def _stream(src, dst, chunk_size=64, n_v=N_V, events=None):
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, events=events, chunk_size=chunk_size,
                        table=IdentityVertexTable(n_v)),
        n_v,
    )


def _host_components(src, dst):
    parent = {}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for u, v in zip(src.tolist(), dst.tolist()):
        parent.setdefault(u, u)
        parent.setdefault(v, v)
        ru, rv = find(u), find(v)
        if ru != rv:
            parent[max(ru, rv)] = min(ru, rv)
    comps = {}
    for x in parent:
        comps.setdefault(find(x), set()).add(x)
    return sorted(sorted(c) for c in comps.values())


def _pairs_to_labels(verts, roots, n_v):
    lab = np.full(n_v, -1, np.int32)
    lab[verts] = roots
    return lab


# ------------------------- native layer parity ------------------------- #


def _need_native():
    if not native.sparse_codecs_available():
        pytest.skip("native sparse codecs unavailable")


def test_cc_sparse_native_matches_dense():
    _need_native()
    src, dst = _rand_edges(n_e=2000, seed=3)
    valid = np.ones(src.shape[0], bool)
    valid[::7] = False
    dense = native.cc_chunk_combine(
        src.astype(np.int32), dst.astype(np.int32), valid, N_V
    )
    v, r = native.cc_chunk_combine_sparse(
        src.astype(np.int32), dst.astype(np.int32), valid, N_V
    )
    # Exactly the touched slots, each with its canonical min-root.
    np.testing.assert_array_equal(
        np.sort(v), np.nonzero(dense >= 0)[0].astype(np.int32)
    )
    np.testing.assert_array_equal(_pairs_to_labels(v, r, N_V), dense)


def test_cc_sparse_numpy_fallback_matches_native():
    _need_native()
    src, dst = _rand_edges(n_e=1500, seed=4)
    v_n, r_n = cc_pairs_numpy(src, dst, None, N_V)
    v_c, r_c = native.cc_chunk_combine_sparse(
        src.astype(np.int32), dst.astype(np.int32), None, N_V
    )
    np.testing.assert_array_equal(
        _pairs_to_labels(v_n, r_n, N_V), _pairs_to_labels(v_c, r_c, N_V)
    )


def test_cc_sparse_empty_chunk():
    _need_native()
    v, r = native.cc_chunk_combine_sparse(
        np.empty(0, np.int32), np.empty(0, np.int32), None, N_V
    )
    assert v.shape == (0,) and r.shape == (0,)
    assert cc_pairs_numpy(np.empty(0, np.int64), np.empty(0, np.int64),
                          None, N_V)[0].shape == (0,)


def test_cc_sparse_rejects_bad_slot():
    _need_native()
    with pytest.raises(ValueError):
        native.cc_chunk_combine_sparse(
            np.array([N_V], np.int32), np.array([0], np.int32), None, N_V
        )
    with pytest.raises(ValueError):
        cc_pairs_numpy(np.array([N_V]), np.array([0]), None, N_V)


def test_parity_sparse_native_matches_dense():
    _need_native()
    from gelly_tpu.library.bipartiteness import parity_pairs_numpy

    rng = np.random.default_rng(5)
    left = rng.integers(0, N_V // 2, 400).astype(np.int32)
    right = (rng.integers(0, N_V // 2, 400) + N_V // 2).astype(np.int32)
    lab_d, par_d, conf_d = native.parity_chunk_combine(
        left, right, None, N_V
    )
    v, r, p, conf_s = native.parity_chunk_combine_sparse(
        left, right, None, N_V
    )
    assert conf_s == bool(conf_d)
    np.testing.assert_array_equal(_pairs_to_labels(v, r, N_V), lab_d)
    touched = lab_d >= 0
    got_p = np.zeros(N_V, np.uint8)
    got_p[v] = p
    np.testing.assert_array_equal(got_p[touched], par_d[touched])
    # numpy fallback agrees too
    v_n, r_n, p_n, conf_n = parity_pairs_numpy(left, right, None, N_V)
    assert conf_n == conf_s
    np.testing.assert_array_equal(
        _pairs_to_labels(v_n, r_n, N_V), lab_d
    )
    got_pn = np.zeros(N_V, np.uint8)
    got_pn[v_n] = p_n
    np.testing.assert_array_equal(got_pn[touched], par_d[touched])
    # Odd cycle flags conflict on the sparse paths.
    tri = np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32)
    assert native.parity_chunk_combine_sparse(*tri, None, N_V)[3]
    assert parity_pairs_numpy(*tri, None, N_V)[3]


@pytest.mark.parametrize("with_deletions", [False, True])
def test_degree_sparse_native_matches_dense(with_deletions):
    _need_native()
    from gelly_tpu.library.degrees import degree_pairs_numpy

    rng = np.random.default_rng(6)
    n_e = 800
    src = rng.integers(0, N_V, n_e).astype(np.int32)
    dst = rng.integers(0, N_V, n_e).astype(np.int32)
    ev = np.zeros(n_e, np.int8)
    if with_deletions:
        ev[rng.random(n_e) < 0.3] = 1
    dense = native.degree_chunk_deltas(src, dst, ev, None, N_V, True, True)
    v, d = native.degree_chunk_deltas_sparse(
        src, dst, ev, None, N_V, True, True
    )
    got = np.zeros(N_V, np.int32)
    got[v] = d
    np.testing.assert_array_equal(got, dense)
    assert (d != 0).all()  # zero net deltas omitted
    v_n, d_n = degree_pairs_numpy(src, dst, ev, None, N_V, True, True)
    got_n = np.zeros(N_V, np.int32)
    got_n[v_n] = d_n
    np.testing.assert_array_equal(got_n, dense)


# ----------------------------- end to end ----------------------------- #


def test_cc_sparse_codec_end_to_end():
    src, dst = _rand_edges()
    oracle = _host_components(src, dst)
    for mesh, me, fb in [(mesh_lib.make_mesh(1), 2, 1),
                         (mesh_lib.make_mesh(1), 4, 4),
                         (mesh_lib.make_mesh(8), 8, 8)]:
        agg = connected_components(N_V, merge="gather", codec="sparse")
        s = _stream(src, dst)
        labels = s.aggregate(agg, mesh=mesh, merge_every=me,
                             fold_batch=fb).result()
        assert labels_to_components(labels, s.ctx) == oracle, (me, fb)


def test_cc_sparse_matches_dense_codec():
    src, dst = _rand_edges(n_e=500, seed=2)
    mesh = mesh_lib.make_mesh(1)
    out = {}
    for codec in ("dense", "sparse"):
        agg = connected_components(N_V, merge="gather", codec=codec)
        s = _stream(src, dst)
        out[codec] = np.asarray(
            s.aggregate(agg, mesh=mesh, merge_every=4, fold_batch=4).result()
        )
    np.testing.assert_array_equal(out["dense"], out["sparse"])


def test_bipartiteness_sparse_codec_end_to_end():
    from gelly_tpu.library.bipartiteness import bipartiteness_check

    rng = np.random.default_rng(9)
    left = rng.integers(0, N_V // 2, 256).astype(np.int64)
    right = (rng.integers(0, N_V // 2, 256) + N_V // 2).astype(np.int64)
    for mesh, me, fb in [(mesh_lib.make_mesh(1), 4, 4),
                         (mesh_lib.make_mesh(8), 8, 8)]:
        agg = bipartiteness_check(N_V, codec="sparse")
        s = _stream(left, right, chunk_size=32)
        res = s.aggregate(agg, mesh=mesh, merge_every=me,
                          fold_batch=fb).result()
        assert bool(res.ok)
        col = np.asarray(res.colors)
        assert (col[left] ^ col[right]).all()
    # Odd cycle flips ok.
    src = np.concatenate([left, [1, 2, 3]])
    dst = np.concatenate([right, [2, 3, 1]])
    agg = bipartiteness_check(N_V, codec="sparse")
    s = _stream(src, dst, chunk_size=32)
    res = s.aggregate(agg, mesh=mesh_lib.make_mesh(1), merge_every=4,
                      fold_batch=4).result()
    assert not bool(res.ok)


@pytest.mark.parametrize("with_deletions", [False, True])
def test_degree_sparse_codec_end_to_end(with_deletions):
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(5)
    n_e = 300
    src = rng.integers(0, N_V, n_e).astype(np.int64)
    dst = rng.integers(0, N_V, n_e).astype(np.int64)
    ev = np.zeros(n_e, np.int32)
    if with_deletions:
        ev[rng.random(n_e) < 0.2] = 1
    oracle = np.zeros(N_V, np.int64)
    sign = np.where(ev == 1, -1, 1)
    np.add.at(oracle, src, sign)
    np.add.at(oracle, dst, sign)
    for fb in (1, 4):
        agg = degree_aggregate(N_V, codec="sparse")
        got = np.asarray(
            _stream(src, dst, events=ev).aggregate(
                agg, merge_every=4, fold_batch=fb
            ).result()
        )
        assert (got == oracle).all(), fb


# ------------------------- wire format details ------------------------- #


def test_bucket_stack_payloads():
    payloads = [
        {"v": np.array([1, 2, 3], np.int32), "r": np.array([1, 1, 1], np.int32),
         "flag": np.bool_(True)},
        {"v": np.empty(0, np.int32), "r": np.empty(0, np.int32),
         "flag": np.bool_(False)},
    ]
    out = bucket_stack_payloads(payloads, {"v": -1, "r": 0}, min_bucket=4)
    assert out["v"].shape == (2, 4)
    np.testing.assert_array_equal(out["v"][0], [1, 2, 3, -1])
    np.testing.assert_array_equal(out["v"][1], [-1, -1, -1, -1])
    np.testing.assert_array_equal(out["r"][0], [1, 1, 1, 0])
    np.testing.assert_array_equal(out["flag"], [True, False])
    # Bucket rounds up to the next power of two past min_bucket.
    big = [{"v": np.zeros(37, np.int32), "r": np.zeros(37, np.int32)}]
    assert bucket_stack_payloads(big, {"v": -1, "r": 0},
                                 min_bucket=4)["v"].shape == (1, 64)


def test_payload_bytes_track_touched_not_capacity():
    # The sparse payload for a chunk touching t vertices over a 2^24 slot
    # space is ~2 * next_pow2(t) * 4 bytes — nowhere near n_v * 4.
    n_v = 1 << 24
    agg = connected_components(n_v, merge="gather")  # auto -> sparse
    assert agg.stack_payloads is not None
    rng = np.random.default_rng(1)
    src = rng.integers(0, n_v, 4096).astype(np.int64)
    dst = rng.integers(0, n_v, 4096).astype(np.int64)
    from gelly_tpu.core.chunk import make_chunk

    chunk = make_chunk(src, dst, device=False)
    payload = agg.host_compress(chunk)
    stacked = agg.stack_payloads([payload])
    nbytes = sum(a.nbytes for a in stacked.values())
    assert nbytes <= 2 * 4 * (1 << 13)  # 2 arrays * 4B * bucket(8192)
    assert nbytes < n_v  # << dense payload (n_v * 4 bytes)


def test_auto_codec_threshold():
    from gelly_tpu.library.connected_components import (
        SPARSE_CODEC_MIN_CAPACITY,
    )

    small = connected_components(N_V)
    big = connected_components(SPARSE_CODEC_MIN_CAPACITY)
    assert small.stack_payloads is None  # dense
    assert big.stack_payloads is not None  # sparse


def test_compact_union_branch_end_to_end():
    # At vertex_capacity >= 4 * lane count the sparse folds take the
    # compacted-root-space unions (union_pairs_compact /
    # union_pairs_parity_compact); run CC + bipartiteness end-to-end in
    # that regime against oracles.
    from gelly_tpu.library.bipartiteness import bipartiteness_check

    n_v = 1 << 16
    rng = np.random.default_rng(51)
    src = rng.integers(0, n_v, 3000).astype(np.int64)
    dst = rng.integers(0, n_v, 3000).astype(np.int64)

    agg = connected_components(n_v, merge="gather", codec="sparse")
    s = _stream(src, dst, chunk_size=512, n_v=n_v)
    labels = s.aggregate(agg, merge_every=2, fold_batch=2).result()
    assert labels_to_components(labels, s.ctx) == _host_components(src, dst)

    left = rng.integers(0, n_v // 2, 2000).astype(np.int64)
    right = (rng.integers(0, n_v // 2, 2000) + n_v // 2).astype(np.int64)
    agg2 = bipartiteness_check(n_v, codec="sparse")
    s2 = _stream(left, right, chunk_size=512, n_v=n_v)
    res = s2.aggregate(agg2, merge_every=2, fold_batch=2).result()
    assert bool(res.ok)
    col = np.asarray(res.colors)
    assert (col[left] ^ col[right]).all()
    # Odd cycle deep in the stream flips ok through the compact branch.
    s3 = _stream(np.concatenate([left, [1, 2, 3]]),
                 np.concatenate([right, [2, 3, 1]]),
                 chunk_size=512, n_v=n_v)
    res3 = s3.aggregate(bipartiteness_check(n_v, codec="sparse"),
                        merge_every=2, fold_batch=2).result()
    assert not bool(res3.ok)
