"""Weighted matching, degree distribution (with deletions), iterative CC —
parity with the reference's example pipelines and ITCase data
(M/example/CentralizedWeightedMatching.java, DegreeDistribution.java,
IterativeConnectedComponents.java; T/util/ExamplesTestData.java:36-60)."""

import numpy as np
import pytest

from gelly_tpu import EDGE_ADDITION, EDGE_DELETION, edge_stream_from_edges
from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.library.degrees import degree_distribution
from gelly_tpu.library.iterative_cc import IterativeCCStream
from gelly_tpu.library.matching import weighted_matching


# ---------------- weighted matching ---------------- #


def reference_matching(edges):
    """Host oracle: the reference's exact sequential algorithm
    (CentralizedWeightedMatching.java:76-107)."""
    matching: set = set()
    for u, v, w in edges:
        coll = {e for e in matching if u in e[:2] or v in e[:2]}
        if w > 2 * sum(e[2] for e in coll):
            matching -= coll
            matching.add((u, v, w))
    return {(min(a, b), max(a, b), w) for a, b, w in matching}


@pytest.mark.parametrize("chunk_size", [1, 4, 16])
def test_matching_parity_with_reference_oracle(chunk_size):
    rng = np.random.default_rng(2)
    edges = [
        (int(a), int(b), float(w))
        for (a, b), w in zip(
            rng.integers(0, 20, (50, 2)), rng.integers(1, 100, 50)
        )
        if a != b
    ]
    s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=chunk_size)
    got = {(min(a, b), max(a, b), w)
           for a, b, w in weighted_matching(s).final_matching()}
    assert got == reference_matching(edges)


def test_matching_eviction():
    # Heavy edge evicts two light collisions only if > 2x their sum.
    edges = [(1, 2, 10.0), (3, 4, 10.0), (2, 3, 45.0)]
    s = edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=3)
    got = weighted_matching(s).final_matching()
    assert got == [(2, 3, 45.0)]
    # Not heavy enough: keeps the existing matching.
    edges2 = [(1, 2, 10.0), (3, 4, 10.0), (2, 3, 20.0)]
    s2 = edge_stream_from_edges(edges2, vertex_capacity=8, chunk_size=3)
    assert sorted(weighted_matching(s2).final_matching()) == [
        (1, 2, 10.0), (3, 4, 10.0)
    ]


def test_matching_f32_f64_threshold_divergence():
    """Pin exactly WHERE the device (f32) and host (f64) matching paths
    diverge (VERDICT r4 item 10): the eviction test ``w > 2*(wu + wv)``
    with f32-exact weights whose SUM is not f32-exact. ``1.0 + 3*2^-24``
    rounds UP in f32 (ties-to-even), so the f32 threshold sits one ulp
    above the f64 one; a challenger between the two is taken by the host
    path (reference-exact, Java doubles,
    CentralizedWeightedMatching.java:68-108) and rejected by the
    device-resident f32 path. Both behaviors are documented; this test
    asserts each stays put."""
    b, c = 1.0, 3 * 2**-24
    w = 2 + 2**-21
    # Preconditions: all weights f32-exact; w straddles the two thresholds.
    assert all(float(np.float32(x)) == x for x in (b, c, w))
    assert w > 2.0 * (b + c)
    assert not (
        np.float32(w) > np.float32(2.0) * (np.float32(b) + np.float32(c))
    )
    edges = [(0, 1, b), (2, 3, c), (1, 3, w)]

    def stream():
        return edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=4)

    # Host (f64, reference-exact): the challenger evicts both incumbents.
    host = weighted_matching(stream()).final_matching()
    assert host == [(1, 3, w)]
    # Device (f32): the rounded-up collision sum rejects the challenger
    # and both incumbents survive (weights are f32-exact, so the decoded
    # matching compares exactly).
    dev = weighted_matching(stream(), device=True).final_matching()
    assert dev == [(0, 1, b), (2, 3, c)]


def test_matching_native_fold_matches_python_fallback(monkeypatch):
    """The C++ fold (native/matching.cc) and the Python host loop must
    produce identical final matchings AND identical ordered event streams."""
    import gelly_tpu.library.matching as M

    rng = np.random.default_rng(11)
    n_e, n_v = 2000, 128
    edges = [
        (int(a), int(b), float(w))
        for a, b, w in zip(
            rng.integers(0, n_v, n_e), rng.integers(0, n_v, n_e),
            rng.integers(1, 500, n_e),
        )
    ]

    def run():
        s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=64)
        ws = weighted_matching(s)
        evs = list(ws.events())
        return evs, sorted(ws.final_matching())

    monkeypatch.setattr(M, "_NATIVE", False)  # force the Python loop
    evs_py, fin_py = run()
    monkeypatch.setattr(M, "_NATIVE", None)  # re-probe the native kernel
    if not M._native_ok():
        pytest.skip("native toolchain unavailable")
    evs_nat, fin_nat = run()
    assert fin_nat == fin_py
    assert evs_nat == evs_py


def test_matching_half_approximation_bound():
    rng = np.random.default_rng(8)
    edges = [
        (int(a), int(b), float(w))
        for (a, b), w in zip(
            rng.integers(0, 12, (40, 2)), rng.integers(1, 50, 40)
        )
        if a != b
    ]
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=8)
    greedy = weighted_matching(s).total_weight()
    # brute-force optimal matching on the deduped best-weight edge set
    best: dict = {}
    for u, v, w in edges:
        k = (min(u, v), max(u, v))
        best[k] = max(best.get(k, 0), w)
    items = list(best.items())

    def brute(i, used):
        if i == len(items):
            return 0.0
        (u, v), w = items[i]
        skip = brute(i + 1, used)
        if u not in used and v not in used:
            return max(skip, w + brute(i + 1, used | {u, v}))
        return skip

    opt = brute(0, frozenset())
    assert greedy * 2 >= opt * 0.999  # ½-approximation guarantee


# ---------------- degree distribution ---------------- #

# ExamplesTestData.DEGREES_DATA (:36-38): events with +/-
DEGREES_DATA = [
    (1, 2, 0), (2, 3, 0), (1, 4, 0), (2, 3, 1), (3, 4, 0), (1, 2, 1),
]
# DEGREES_DATA_ZERO adds a second deletion of 2-3 (:48-51)
DEGREES_DATA_ZERO = DEGREES_DATA + [(2, 3, 1)]


def event_stream(data, chunk_size=2):
    src = np.array([e[0] for e in data])
    dst = np.array([e[1] for e in data])
    ev = np.array([e[2] for e in data], np.int8)
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, events=ev, chunk_size=chunk_size), 16
    )


def test_degree_distribution_final_state():
    # Final live edges 1-4, 3-4 -> degrees {1:1, 3:1, 4:2} -> dist {1:2, 2:1}.
    s = event_stream(DEGREES_DATA)
    assert degree_distribution(s, max_degree=8).final_distribution() == {
        1: 2, 2: 1
    }


def test_degree_distribution_deletion_to_zero():
    # The extra 2-3 deletion drives vertex 3 to zero -> dist {1:1, 2:1}
    # (the ITCase's DEGREES_RESULT_ZERO final "(1,1)").
    s = event_stream(DEGREES_DATA_ZERO)
    assert degree_distribution(s, max_degree=8).final_distribution() == {
        1: 1, 2: 1
    }


def test_degree_stream_honors_deletions():
    s = event_stream(DEGREES_DATA)
    assert s.get_degrees().final_degrees() == {1: 1, 2: 0, 3: 1, 4: 2}


# ---------------- iterative CC ---------------- #


def test_iterative_cc_matches_unionfind(reference_edges):
    from gelly_tpu.library.connected_components import (
        connected_components, labels_to_components,
    )

    s = edge_stream_from_edges(
        [(a, b) for a, b, _ in reference_edges] + [(6, 7), (8, 9)],
        vertex_capacity=32, chunk_size=2,
    )
    it_labels = IterativeCCStream(s).final_labels()
    uf_labels = s.aggregate(connected_components(32), merge_every=2).result()
    assert labels_to_components(it_labels, s.ctx) == labels_to_components(
        uf_labels, s.ctx
    )


def test_iterative_cc_transitive_across_chunks():
    # Regression: component merged by a later chunk must relabel members
    # seen only in earlier chunks (the feedback-channel semantics).
    s = edge_stream_from_edges(
        [(5, 9), (7, 8), (1, 5), (0, 7)], vertex_capacity=16, chunk_size=1
    )
    labels = np.asarray(IterativeCCStream(s).final_labels())
    slot = {int(r): i for i, r in enumerate(s.ctx.table._rev.tolist())}
    assert labels[slot[9]] == labels[slot[1]] == labels[slot[5]]
    assert labels[slot[8]] == labels[slot[0]] == labels[slot[7]]
    assert labels[slot[9]] != labels[slot[8]]


def test_matching_device_path_matches_host():
    rng = np.random.default_rng(12)
    edges = [
        (int(a), int(b), float(w))
        for (a, b), w in zip(
            rng.integers(0, 16, (40, 2)), rng.integers(1, 100, 40)
        )
        if a != b
    ]
    host = weighted_matching(
        edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=8)
    ).final_matching()
    dev = weighted_matching(
        edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=8),
        device=True,
    ).final_matching()
    assert host == dev


def test_matching_event_stream():
    edges = [(1, 2, 10.0), (3, 4, 10.0), (2, 3, 45.0)]
    s = edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=1)
    evs = list(weighted_matching(s).events())
    kinds = [(e.type, frozenset((e.src, e.dst))) for e in evs]
    assert kinds == [
        ("ADD", frozenset({1, 2})),
        ("ADD", frozenset({3, 4})),
        ("REMOVE", frozenset({1, 2})),
        ("REMOVE", frozenset({3, 4})),
        ("ADD", frozenset({2, 3})),
    ]


def test_matching_same_edge_rematch_single_remove():
    # Evicting the edge (u,v) itself must emit exactly one REMOVE.
    s = edge_stream_from_edges([(1, 2, 10.0), (1, 2, 45.0)],
                               vertex_capacity=8, chunk_size=1)
    wm = weighted_matching(s)
    evs = [(e.type, frozenset((e.src, e.dst)), e.weight)
           for e in wm.events()]
    assert evs == [
        ("ADD", frozenset({1, 2}), 10.0),
        ("REMOVE", frozenset({1, 2}), 10.0),
        ("ADD", frozenset({1, 2}), 45.0),
    ]
    # events() drain is cached: total_weight must not recompute.
    assert wm.total_weight() == 45.0


def test_sharded_degrees_matches_host(devices):
    from gelly_tpu.library.degrees import sharded_degrees
    from gelly_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(7)
    edges = [(int(a), int(b)) for a, b in rng.integers(0, 60, (200, 2))]
    m = mesh_lib.make_mesh(8)
    s1 = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=32)
    got = sharded_degrees(s1, mesh=m).final_degrees()
    s2 = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=32)
    expected = s2.get_degrees().final_degrees()
    assert got == expected


def test_sharded_degrees_with_deletions(devices):
    from gelly_tpu.library.degrees import sharded_degrees
    from gelly_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh(8)
    src = np.array([1, 1, 1]); dst = np.array([2, 3, 2])
    ev = np.array([0, 0, 1], np.int8)
    s = edge_stream_from_source(
        EdgeChunkSource(src, dst, events=ev, chunk_size=2), 64
    )
    assert sharded_degrees(s, mesh=m).final_degrees() == {
        1: 1, 2: 0, 3: 1
    }
