"""Core substrate tests: chunks, ingestion, vertex tables, stream transforms.

Coverage model: the reference's operation tests
(T/test/operations/TestGraphStreamCreation.java, TestMapEdges, TestFilterEdges,
TestDistinct, TestGetDegrees, TestNumberOfEntities — SURVEY.md §4 tier 2),
asserted on the canonical 5-vertex/7-edge fixture.
"""

import numpy as np
import pytest

from gelly_tpu import (
    EdgeChunk,
    TimeCharacteristic,
    VertexTable,
    edge_stream_from_edges,
    make_chunk,
)
from gelly_tpu.core.io import parse_edge_list_text


def stream_of(edges, **kw):
    kw.setdefault("vertex_capacity", 64)
    kw.setdefault("chunk_size", 4)
    return edge_stream_from_edges(edges, **kw)


def test_chunk_padding_and_masks():
    c = make_chunk([1, 2], [3, 4], capacity=8)
    assert c.capacity == 8
    assert int(c.num_valid()) == 2
    r = c.reverse()
    assert np.asarray(r.src)[:2].tolist() == [3, 4]
    u = c.undirected()
    assert u.capacity == 16
    assert int(u.num_valid()) == 4


def test_vertex_table_densifies_sparse_ids():
    t = VertexTable()
    slots = t.encode(np.array([100, 7, 100, 9**10]))
    assert slots.tolist() == [0, 1, 0, 2]
    assert t.decode(np.array([2, 0])).tolist() == [9**10, 100]
    assert t.lookup(np.array([7, 12345])).tolist() == [1, -1]


def test_parse_edge_list_with_comments():
    src, dst, val = parse_edge_list_text(
        "% a comment\n1 2\n# another\n3 4\n\n5 6\n"
    )
    assert src.tolist() == [1, 3, 5]
    assert dst.tolist() == [2, 4, 6]
    assert val is None
    src, dst, val = parse_edge_list_text("1,2,0.5\n3,4,1.5", delimiter=",",
                                         num_value_cols=1)
    assert val.tolist() == [0.5, 1.5]


def test_stream_roundtrip_preserves_edges(reference_edges):
    got = stream_of(reference_edges).collect_edges()
    assert sorted(got) == sorted(reference_edges)


def test_ingestion_vs_event_time(reference_edges):
    s = stream_of(reference_edges)
    ts = np.concatenate([np.asarray(c.ts)[np.asarray(c.valid)] for c in s])
    assert ts.tolist() == list(range(7))  # arrival index
    s2 = stream_of(
        reference_edges,
        time=TimeCharacteristic.EVENT,
        ts_fn=lambda s_, d_, v: v.astype(np.int64),
    )
    ts2 = np.concatenate([np.asarray(c.ts)[np.asarray(c.valid)] for c in s2])
    assert ts2.tolist() == [12, 13, 23, 34, 35, 45, 51]


def test_map_edges(reference_edges):
    # TestMapEdges: add one to edge values.
    got = stream_of(reference_edges).map_edges(lambda s, d, v: v + 1).collect_edges()
    assert sorted(v for _, _, v in got) == [13.0, 14.0, 24.0, 35.0, 36.0, 46.0, 52.0]


def test_filter_edges(reference_edges):
    got = stream_of(reference_edges).filter_edges(
        lambda s, d, v: v > 30
    ).collect_edges()
    assert sorted(got) == [(3, 4, 34.0), (3, 5, 35.0), (4, 5, 45.0), (5, 1, 51.0)]


def test_filter_vertices_keeps_edge_iff_both_pass(reference_edges):
    # ApplyVertexFilterToEdges: both endpoints must pass.
    got = stream_of(reference_edges).filter_vertices(lambda v: v > 2).collect_edges()
    assert sorted(got) == [(3, 4, 34.0), (3, 5, 35.0), (4, 5, 45.0)]


def test_reverse_undirected(reference_edges):
    rev = stream_of(reference_edges).reverse().collect_edges()
    assert sorted((s, d) for s, d, _ in rev) == sorted(
        (d, s) for s, d, _ in reference_edges
    )
    und = stream_of(reference_edges).undirected().collect_edges()
    assert len(und) == 14


def test_union(reference_edges):
    s1 = stream_of(reference_edges[:3])
    from gelly_tpu.core.io import chunks_from_edges
    from gelly_tpu.core.stream import EdgeStream

    # Second stream must share the context/table.
    src2 = chunks_from_edges(reference_edges[3:], chunk_size=4,
                             table=s1.ctx.table)
    s2 = EdgeStream(lambda: iter(src2), s1.ctx)
    got = s1.union(s2).collect_edges()
    assert sorted(got) == sorted(reference_edges)


@pytest.mark.parametrize("device", [False, True])
def test_distinct(device):
    # TestDistinct: duplicated input collapses to unique (src, dst) pairs.
    # Duplicates land both within one chunk and across chunk boundaries
    # (chunk_size=2); host and device paths must agree exactly.
    edges = [(1, 2, 1.0), (1, 2, 1.0), (2, 3, 1.0), (1, 2, 9.0), (3, 4, 1.0),
             (2, 3, 5.0)]
    got = stream_of(edges, chunk_size=2).distinct(device=device) \
        .collect_edges()
    assert sorted((s, d) for s, d, _ in got) == [(1, 2), (2, 3), (3, 4)]
    # first-wins: the surviving (1,2) is the first one (val 1.0)
    vals = {(s, d): v for s, d, v in got}
    assert vals[(1, 2)] == 1.0


def test_distinct_host_matches_device_random():
    rng = np.random.default_rng(17)
    edges = [(int(a), int(b), float(i))
             for i, (a, b) in enumerate(rng.integers(0, 12, (300, 2)))]
    host = stream_of(edges, chunk_size=32).distinct().collect_edges()
    dev = stream_of(edges, chunk_size=32).distinct(device=True) \
        .collect_edges()
    assert sorted(host) == sorted(dev)
    assert len(host) == len({(s, d) for s, d, _ in edges})


def test_get_vertices(reference_edges):
    s = stream_of(reference_edges)
    seen = []
    for upd in s.get_vertices():
        seen.extend(i for i, _ in upd.to_pairs(s.ctx))
    assert sorted(seen) == [1, 2, 3, 4, 5]
    assert len(seen) == 5  # no duplicates across chunks


def test_degrees(reference_edges):
    # TestGetDegrees final values.
    s = stream_of(reference_edges, chunk_size=3)
    assert s.get_degrees().final_degrees() == {1: 3, 2: 2, 3: 4, 4: 2, 5: 3}
    s = stream_of(reference_edges, chunk_size=3)
    assert s.get_out_degrees().final_degrees() == {1: 2, 2: 1, 3: 2, 4: 1, 5: 1}
    s = stream_of(reference_edges, chunk_size=3)
    assert s.get_in_degrees().final_degrees() == {1: 1, 2: 1, 3: 2, 4: 1, 5: 2}


def test_degrees_continuously_improving(reference_edges):
    # The degree stream re-emits updated values as edges arrive
    # (DegreeMapFunction semantics at chunk granularity).
    s = stream_of(reference_edges, chunk_size=1)
    updates = [dict(u.to_pairs(s.ctx)) for u in s.get_degrees()]
    assert updates[0] == {1: 1, 2: 1}          # after (1,2)
    assert updates[1] == {1: 2, 3: 1}          # after (1,3)
    assert updates[-1][1] == 3 and updates[-1][5] == 3  # after (5,1)


def test_counts(reference_edges):
    s = stream_of(reference_edges, chunk_size=2)
    assert list(s.number_of_edges())[-1] == 7
    s = stream_of(reference_edges, chunk_size=2)
    counts = list(s.number_of_vertices())
    assert counts[-1] == 5
    assert counts == sorted(counts)  # monotone, emit-on-change


def test_deletion_events_decrement_degrees():
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source

    def make():
        src = EdgeChunkSource(
            np.array([1, 1, 1]), np.array([2, 3, 2]),
            events=np.array([0, 0, 1], np.int8), chunk_size=2,
        )
        return edge_stream_from_source(src, vertex_capacity=16)

    assert make().get_degrees().final_degrees() == {1: 1, 2: 0, 3: 1}
    # numberOfEdges tracks the live graph: 2 adds - 1 delete = 1.
    assert list(make().number_of_edges())[-1] == 1


def test_make_chunk_raw_width_promotion():
    # Raw ids keep their source integer width, but a wider raw_dst must
    # promote BOTH raw fields (an i64 id must never truncate through i32).
    from gelly_tpu.core.chunk import make_chunk

    c = make_chunk(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                   raw_dst=np.array([2 ** 40, 3], np.int64), capacity=2,
                   device=False)
    assert c.raw_dst.dtype == np.int64 and int(c.raw_dst[0]) == 2 ** 40
    assert c.raw_src.dtype == np.int64
    c2 = make_chunk(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    capacity=4, device=False)
    assert c2.raw_src.dtype == np.int32  # identity: no conversion pass


def test_vertex_capacity_overflow_raises(reference_edges):
    s = stream_of(reference_edges, vertex_capacity=3)
    with pytest.raises(ValueError, match="overflow|capacity"):
        s.collect_edges()


def test_get_vertices_emits_raw_ids():
    big = 5_000_000_000
    s = stream_of([(big, 7, 1.0)])
    upds = list(s.get_vertices())
    ids = [i for u in upds for i, _ in u.to_pairs(s.ctx)]
    assert sorted(ids) == [7, big]
    # values carry the raw id too, not internal slots
    vals = [int(v) for u in upds for _, v in u.to_pairs(s.ctx)]
    assert sorted(vals) == [7, big]
