"""Coordinated multi-host recovery — in-process protocol tier.

Multiple "hosts" are plain :class:`Coordinator` instances with explicit
:class:`HostIdentity` sharing one store directory (threads where the
protocol needs concurrency). The real 2-process gloo-mesh tier —
jax.distributed + SIGKILL mid-stream — lives in
``tests/test_coordinated_recovery.py``; everything deterministic about
the protocol itself (barrier agreement, 2PC abort, leader rotation,
manifest/mixed-epoch validation, degraded adoption, fault injection,
the cadenced path flatten) is proven here, fast.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from gelly_tpu.engine import coordination as coord_mod
from gelly_tpu.engine import faults
from gelly_tpu.engine.coordination import (
    CheckpointStore,
    CoordinationConfig,
    Coordinator,
    CoordinationError,
    HostIdentity,
    LeaseBoard,
    ManifestCorruptError,
    MixedEpochError,
)
from gelly_tpu.engine.resilience import (
    CheckpointManager,
    ResilienceConfig,
    ResilientRunner,
    Watchdog,
    WatchdogTimeout,
)
from gelly_tpu.obs import bus as obs_bus


@pytest.fixture(autouse=True)
def _reset_active_coordinator():
    """Coordinators register themselves for heartbeat/trace leadership
    attribution; tests here construct many without closing — clear the
    registry so no leadership flag leaks across tests/files."""
    yield
    coord_mod._ACTIVE = None


def _cfg(**kw):
    kw.setdefault("lease_ttl", 2.0)
    kw.setdefault("poll_s", 0.005)
    kw.setdefault("barrier_timeout", 10.0)
    # In-process tests simulate silent host death by simply STOPPING a
    # coordinator's calls, so the background lease thread (which would
    # keep the "dead" host alive) is opted out here; its semantics get
    # a dedicated test below, and the gloo subprocess tier runs with it
    # on (SIGKILL kills the thread — the production shape).
    kw.setdefault("lease_thread", False)
    return CoordinationConfig(**kw)


def _fast(**kw):
    kw.setdefault("checkpoint_every_chunks", 4)
    kw.setdefault("watchdog_timeout", 30.0)
    return ResilienceConfig(**kw)


def _run_hosts(n, body):
    """Run ``body(k)`` for each host index on its own thread; re-raise
    the first failure (coordination is symmetric — one host erroring
    usually strands the others in a bounded wait)."""
    errs = []

    def wrapped(k):
        try:
            body(k)
        except BaseException as e:  # noqa: BLE001
            errs.append((k, e))

    ts = [threading.Thread(target=wrapped, args=(k,)) for k in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    if errs:
        raise errs[0][1]


# ---------------------------------------------------------------------- #
# identities, leases, store plumbing


def test_host_identity_validation():
    with pytest.raises(ValueError):
        HostIdentity(2, 2)
    with pytest.raises(ValueError):
        HostIdentity(-1, 2)
    with pytest.raises(ValueError):
        HostIdentity(0, 0)
    ident = coord_mod.detect_host_identity()
    assert ident.process_index == 0 and ident.process_count == 1


def test_lease_board_liveness_and_expiry(tmp_path):
    store = CheckpointStore(str(tmp_path))
    now = [100.0]
    b0 = LeaseBoard(store, 0, ttl=1.0, clock=lambda: now[0])
    b1 = LeaseBoard(store, 1, ttl=1.0, clock=lambda: now[0])
    assert b0.beat() and b1.beat()
    assert b0.live() == {0, 1}
    assert not b0.expired(1)
    assert not b0.expired(7)  # absent lease = unknown, never "dead"
    now[0] += 0.2
    assert not b1.beat()  # rate-limited to ttl/3
    now[0] += 2.0
    b0.beat()
    assert b0.live() == {0}
    assert b0.expired(1)


def test_store_atomic_writes_leave_no_tmp(tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.write_intent(1, 0, 5)
    store.write_prepared(1, 0, 5)
    store.commit(1, 5, 1)
    leftovers = [
        f for _, _, files in os.walk(tmp_path) for f in files
        if f.endswith(".tmp")
    ]
    assert leftovers == []
    man = store.read_manifest()
    assert man["epoch"] == 1 and man["position"] == 5
    assert man["hosts"] == [0]


def test_torn_manifest_rejected(tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.commit(3, 12, 2)
    with open(store.manifest_path, "r+") as f:
        f.truncate(os.path.getsize(store.manifest_path) // 2)
    with pytest.raises(ManifestCorruptError, match="torn|unparsable"):
        store.read_manifest()
    # schema damage is rejected too, distinctly from a tear
    with open(store.manifest_path, "w") as f:
        json.dump({"version": 1, "epoch": 3}, f)
    with pytest.raises(ManifestCorruptError, match="position"):
        store.read_manifest()


def test_mixed_epoch_store_rejected(tmp_path):
    """Validation targets the SHARDS (the fsync-durable, deterministic
    truth — votes are a commit artifact a crashed re-attempt may have
    overwritten): a committed epoch missing any host's shard at the
    manifest position is rejected."""
    store = CheckpointStore(str(tmp_path))
    state = {"x": np.arange(4, dtype=np.int64)}
    # host 0's shard at position 8; host 1 "died mid-write": its shard
    # exists only at an OLDER position (epoch surgery / partial copy).
    store.write_shard(2, 0, state, 8)
    store.write_shard(2, 1, state, 4)
    store.commit(2, 8, 2)  # a manifest 2PC would never have written
    man = store.read_manifest()
    with pytest.raises(MixedEpochError, match="missing"):
        store.validate_epoch(man)
    store.write_shard(2, 1, state, 8)
    store.validate_epoch(man)  # consistent at last
    # a shard whose INTERNAL position header disagrees is caught at
    # load (the recover path), not by the existence scan
    state2, pos, _ = store.load_shard(2, 1, 8)
    assert pos == 8


# ---------------------------------------------------------------------- #
# barrier + 2PC


def test_barrier_agrees_on_max_and_commits(tmp_path):
    results = {}

    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        epoch, agreed = co.agree_position(3 + k)  # proposals 3 and 4
        man = co.publish(epoch, {"x": np.arange(4) + k}, agreed)
        results[k] = (epoch, agreed, man["epoch"], man["position"])

    with obs_bus.scope() as bus:
        _run_hosts(2, body)
    assert results[0] == results[1] == (1, 4, 1, 4)
    counters = bus.snapshot()["counters"]
    assert counters["coordination.barrier_agreed"] == 2
    assert counters["coordination.prepared"] == 2
    assert counters["coordination.committed"] == 1


def test_epoch_aborts_when_host_dies_before_preparing(tmp_path):
    """2PC phase-1 death: the missing host's lease expires, the leader
    aborts the epoch, and NO manifest exists — recovery sees the
    previous committed state, never half an epoch."""
    cfg = _cfg(lease_ttl=0.5, barrier_timeout=5.0)
    co0 = Coordinator(str(tmp_path), HostIdentity(0, 2), cfg)
    co1 = Coordinator(str(tmp_path), HostIdentity(1, 2), cfg)
    # Both agree on the barrier...
    out = {}

    def body(k):
        co = (co0, co1)[k]
        out[k] = co.agree_position(6)

    _run_hosts(2, body)
    assert out[0] == out[1]
    epoch, agreed = out[0]
    # ...but host 1 dies before writing its shard: its lease lapses.
    time.sleep(0.7)
    with pytest.raises(CoordinationError, match="died before preparing"):
        co0.publish(epoch, {"x": np.arange(2)}, agreed)
    assert CheckpointStore(str(tmp_path)).read_manifest() is None


def test_leader_rotation_commits_after_leader_death(tmp_path):
    """Leader dies BETWEEN phases (its shard is prepared, the manifest
    is not written): the next-lowest live host observes the lease
    expiry, becomes leader, and completes the commit — rotation, not
    abort. Leadership loss is published on the bus."""
    cfg = _cfg(lease_ttl=0.4, barrier_timeout=10.0)
    with obs_bus.scope() as bus:
        co0 = Coordinator(str(tmp_path), HostIdentity(0, 2), cfg)
        co1 = Coordinator(str(tmp_path), HostIdentity(1, 2), cfg)
        out = {}

        def body(k):
            out[k] = (co0, co1)[k].agree_position(9)

        _run_hosts(2, body)
        epoch, agreed = out[0]
        # Host 0 (the leader) prepares its shard, then dies silently.
        co0.store.write_shard(epoch, 0, {"x": np.arange(3)}, agreed)
        co0.store.write_prepared(epoch, 0, agreed)
        time.sleep(0.6)  # let the leader's lease lapse
        man = co1.publish(epoch, {"x": np.arange(3) + 1}, agreed)
    assert man["epoch"] == epoch and man["position"] == agreed
    assert man["meta"]["committed_by"] == 1
    assert co1.is_leader
    counters = bus.snapshot()["counters"]
    assert counters["coordination.leader_elected"] >= 3  # initial + takeover
    assert counters["coordination.committed"] == 1
    CheckpointStore(str(tmp_path)).validate_epoch(man)


def test_lease_thread_keeps_host_alive_through_stalls(tmp_path):
    """The background beat thread makes the lease mean PROCESS
    liveness: a host stalled past the ttl (shard write, jit compile)
    is never false-declared dead; close() stops the thread and the
    lease then expires like a real departure."""
    cfg = _cfg(lease_ttl=0.45, lease_thread=True)
    co = Coordinator(str(tmp_path), HostIdentity(0, 2), cfg)
    observer = LeaseBoard(CheckpointStore(str(tmp_path)), 1, ttl=0.45)
    time.sleep(0.7)  # stall with no protocol calls, longer than ttl
    assert not observer.expired(0)
    co.close()
    time.sleep(0.7)
    assert observer.expired(0)


def test_epoch_numbering_derives_from_committed_state(tmp_path):
    """Epochs are ``committed + 1`` — derived from the manifest every
    host reads, never from racy directory listings — and records left
    by a PREVIOUS incarnation in a re-attempted epoch dir are filtered
    by run_id instead of mis-agreeing the barrier."""
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    e1, _ = co.agree_position(2)
    assert e1 == 1
    co.publish(e1, {"x": np.arange(2)}, 2)
    # a crashed incarnation left an uncommitted higher epoch dir plus a
    # stale intent inside the epoch the new incarnation will re-attempt
    os.makedirs(co.store.epoch_dir(7), exist_ok=True)
    co.store.write_intent(2, 1, 999, run_id="e0-stale")
    co2 = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    e2, p2 = co2.agree_position(4)
    # committed(1)+1, stale dir 7 ignored — and the agreed position is
    # 4, not the stale intent's 999 (run_id filter).
    assert (e2, p2) == (2, 4)
    # 2-host shape: the stale peer intent must NOT satisfy the
    # rendezvous (different incarnation) — the barrier times out on the
    # genuinely absent host instead of agreeing on position 999.
    co3 = Coordinator(str(tmp_path), HostIdentity(0, 2),
                      _cfg(barrier_timeout=0.8))
    with pytest.raises(CoordinationError, match="incomplete"):
        co3.agree_position(6)


def test_reattempted_epoch_converges_over_stale_records(tmp_path):
    """A crashed incarnation that shares the restart's run_id (same
    committed base) left intents/votes in the uncommitted next epoch —
    including some from a host that no longer exists. The restart must
    scrub its own leftovers, ignore the out-of-group host's, and drive
    the re-attempted epoch to a clean commit at the FRESH positions."""
    _committed_two_host_store(tmp_path, position=8)
    store = CheckpointStore(str(tmp_path))
    man = store.read_manifest()
    run_id = f"e{man['epoch']}-{man['wall_time']}"
    for h, pos in ((0, 99), (1, 98), (2, 97)):
        store.write_intent(2, h, pos, run_id=run_id)
        store.write_prepared(2, h, pos, run_id=run_id)
    out = {}

    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        _, pos, _ = co.recover(like={"x": np.zeros(4, dtype=np.int64)})
        epoch, agreed = co.agree_position(pos + 4)
        man2 = co.publish(
            epoch, {"x": np.arange(4, dtype=np.int64)}, agreed
        )
        out[k] = (epoch, agreed, man2["position"])

    _run_hosts(2, body)
    assert out[0] == out[1] == (2, 12, 12)


def test_prune_keeps_committed_window(tmp_path):
    store = CheckpointStore(str(tmp_path))
    for e in (1, 2, 3, 4, 5):
        os.makedirs(store.epoch_dir(e), exist_ok=True)
    store.prune(committed=5, keep=2)
    assert store.list_epochs() == [4, 5]


# ---------------------------------------------------------------------- #
# recover: re-join + the degradation rung


def _committed_two_host_store(tmp_path, position=8):
    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        epoch, agreed = co.agree_position(position)
        co.publish(
            epoch, {"x": np.arange(4, dtype=np.int64) * (k + 1)}, agreed
        )

    _run_hosts(2, body)


def test_rejoin_loads_own_shard_at_barrier_position(tmp_path):
    _committed_two_host_store(tmp_path)
    with obs_bus.scope() as bus:
        co = Coordinator(str(tmp_path), HostIdentity(1, 2), _cfg())
        state, pos, _meta = co.recover(
            like={"x": np.zeros(4, dtype=np.int64)}
        )
    assert pos == 8
    np.testing.assert_array_equal(state["x"], np.arange(4) * 2)
    assert bus.snapshot()["counters"]["coordination.rejoins"] == 1


def test_degraded_rejoin_adopts_orphan_shards(tmp_path):
    """The degradation rung: one survivor of a 2-host group re-joins
    with process_count=1, adopts the lost host's shard via the combine,
    and a ``coordination.degradations`` event is published — the stream
    continues at reduced capacity instead of aborting."""
    _committed_two_host_store(tmp_path)
    events = []
    with obs_bus.scope() as bus:
        bus.subscribe(lambda name, f: events.append((name, f)))
        co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
        state, pos, _meta = co.recover(
            like={"x": np.zeros(4, dtype=np.int64)},
            adopt=lambda a, b: {"x": a["x"] + b["x"]},
        )
    assert pos == 8
    np.testing.assert_array_equal(state["x"], np.arange(4) * 3)
    degr = [f for name, f in events if name == "coordination.degradations"]
    assert len(degr) == 1
    assert degr[0]["previous_process_count"] == 2
    assert degr[0]["process_count"] == 1
    assert degr[0]["adopted"] == [1]
    assert degr[0]["capacity_frac"] == 0.5


def test_degraded_rejoin_without_adopt_refuses(tmp_path):
    _committed_two_host_store(tmp_path)
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    with pytest.raises(CoordinationError, match="adopt"):
        co.recover(like={"x": np.zeros(4, dtype=np.int64)})


def test_recover_rejects_mixed_epoch(tmp_path):
    _committed_two_host_store(tmp_path)
    store = CheckpointStore(str(tmp_path))
    man = store.read_manifest()
    os.unlink(store.shard_path(man["epoch"], 1, man["position"]))
    co = Coordinator(str(tmp_path), HostIdentity(0, 2), _cfg())
    with pytest.raises(MixedEpochError):
        co.recover(like={"x": np.zeros(4, dtype=np.int64)})


# ---------------------------------------------------------------------- #
# fault injection inside the protocol (the "barrier" boundary)


@pytest.mark.faults
def test_barrier_fault_raises_inside_agree(tmp_path):
    plan = faults.FaultPlan([faults.Fault("barrier", at=0)])
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    with faults.install(plan):
        with pytest.raises(faults.FaultInjected):
            co.agree_position(3)
    assert ("barrier", 0, "raise") in plan.fired


@pytest.mark.faults
def test_barrier_hang_is_caught_by_watchdog(tmp_path):
    plan = faults.FaultPlan([
        faults.Fault("barrier", at=0, kind="hang", hang_seconds=5.0),
    ])
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    wd = Watchdog(0.3)
    with faults.install(plan):
        with pytest.raises(WatchdogTimeout):
            wd.call(lambda: co.agree_position(3), "barrier")


@pytest.mark.faults
def test_barrier_corrupt_fault_tears_manifest(tmp_path):
    """The post-commit injection point carries the manifest path, so a
    seeded corrupt fault produces exactly the torn manifest recovery
    must reject."""
    # single host: barrier indices are 0=agree, 1=publish, 2=post-commit
    plan = faults.FaultPlan([
        faults.Fault("barrier", at=2, kind="corrupt"),
    ])
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    with faults.install(plan):
        epoch, agreed = co.agree_position(5)
        co.publish(epoch, {"x": np.arange(2)}, agreed)
    assert ("barrier", 2, "corrupt") in plan.fired
    with pytest.raises(ManifestCorruptError):
        CheckpointStore(str(tmp_path)).read_manifest()


@pytest.mark.faults
def test_collective_boundary_fires_at_window_merge():
    """The cross-shard window-close merge is a fault boundary: a seeded
    plan raises inside the engine's merge dispatch."""
    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.engine.aggregation import run_aggregation
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(5)
    edges = [(int(a), int(b)) for a, b in rng.integers(0, 32, (64, 2))]
    stream = edge_stream_from_edges(edges, vertex_capacity=32,
                                    chunk_size=16)
    plan = faults.FaultPlan([faults.Fault("collective", at=0)])
    with faults.install(plan):
        with pytest.raises(faults.FaultInjected):
            run_aggregation(
                degree_aggregate(32), stream, merge_every=2,
            ).result()
    assert ("collective", 0, "raise") in plan.fired


# ---------------------------------------------------------------------- #
# coordinated ResilientRunner (threads = in-process hosts)


def _add_step(s, chunk):
    return s + np.int64(chunk), None


def test_coordinated_runner_end_to_end_and_resume(tmp_path):
    finals = {}

    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        r = ResilientRunner(
            _add_step, list(range(k * 100, k * 100 + 10)), np.int64(0),
            coordinator=co, config=_fast(),
        )
        finals[k] = (int(r.run()), r.stats["checkpoints"])

    _run_hosts(2, body)
    assert finals[0] == (sum(range(10)), 3)          # 4, 8, final 10
    assert finals[1] == (sum(range(100, 110)), 3)
    man = CheckpointStore(str(tmp_path)).read_manifest()
    assert man["position"] == 10 and man["process_count"] == 2

    # resume: both hosts restart, skip everything, recover their state
    def body2(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        r = ResilientRunner(
            _add_step, list(range(k * 100, k * 100 + 10)), np.int64(0),
            coordinator=co, config=_fast(),
        )
        finals[k] = (int(r.run()), r.stats["chunks"],
                     r.stats["resumed_from"])

    _run_hosts(2, body2)
    for k in (0, 1):
        total, chunks, resumed_from = finals[k]
        assert total == sum(range(k * 100, k * 100 + 10))
        assert chunks == 0  # nothing re-folded
        assert resumed_from and resumed_from.endswith("MANIFEST.json")


def test_coordinated_runner_rejects_checkpoint_dir(tmp_path):
    co = Coordinator(str(tmp_path / "store"), HostIdentity(0, 1), _cfg())
    with pytest.raises(ValueError, match="not both"):
        ResilientRunner(
            _add_step, [1, 2], np.int64(0), coordinator=co,
            checkpoint_dir=str(tmp_path / "local"),
        )


def test_coordinated_runner_unequal_partitions_fail_loudly(tmp_path):
    """Hosts whose partitions disagree on the final chunk count must
    surface the skew as CoordinationError, not deadlock or silently
    commit a mixed position."""
    cfg = _cfg(barrier_timeout=2.0)
    errs = {}

    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), cfg)
        r = ResilientRunner(
            _add_step, list(range(8 if k == 0 else 10)), np.int64(0),
            coordinator=co,
            config=_fast(checkpoint_every_chunks=100),
        )
        try:
            r.run()
        except CoordinationError as e:
            errs[k] = str(e)

    _run_hosts(2, body)
    assert errs, "at least one host must observe the skew"
    assert any("equal chunk counts" in v or "incomplete" in v
               for v in errs.values())


def test_degraded_runner_continues_at_reduced_capacity(tmp_path):
    """The acceptance shape: a 2-host committed store, one host
    permanently lost; the survivor re-joins with adopt, continues the
    stream (its own remainder plus the re-routed chunks), and a
    degradations event is published instead of an abort."""
    def body(k):
        co = Coordinator(str(tmp_path), HostIdentity(k, 2), _cfg())
        ResilientRunner(
            _add_step, [k * 10 + i for i in range(8)], np.int64(0),
            coordinator=co, config=_fast(),
        ).run()

    _run_hosts(2, body)
    man = CheckpointStore(str(tmp_path)).read_manifest()
    assert man["position"] == 8
    # host 1 is permanently gone; host 0 re-joins as a 1-host group.
    # Ingest-side re-routing is the caller's job: the survivor's source
    # holds the re-sharded tail (here: 4 fresh chunks past position 8).
    events = []
    with obs_bus.scope() as bus:
        bus.subscribe(lambda name, f: events.append((name, f)))
        co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
        r = ResilientRunner(
            _add_step, lambda pos: iter(range(100, 100 + 12 - pos)),
            np.int64(0), coordinator=co, config=_fast(),
            adopt_state=lambda a, b: a + b,
        )
        final = int(r.run())
    both = sum(i for i in range(8)) + sum(10 + i for i in range(8))
    assert final == both + sum(range(100, 104))
    degr = [f for name, f in events
            if name == "coordination.degradations"]
    assert len(degr) == 1 and degr[0]["capacity_frac"] == 0.5
    man2 = CheckpointStore(str(tmp_path)).read_manifest()
    assert man2["position"] == 12 and man2["process_count"] == 1


# ---------------------------------------------------------------------- #
# atomic checkpoint publish: rotation can never strand zero valid files


@pytest.mark.faults
def test_rotation_never_prunes_fallback_of_torn_newest(tmp_path):
    """keep=1 + a torn final write: before the fix, rotation pruned the
    previous file and the store held ZERO valid checkpoints; now the
    newest file is validated before any pruning."""
    mgr = CheckpointManager(str(tmp_path), keep=1, async_write=False)
    mgr.save(np.int64(10), 4)
    plan = faults.FaultPlan([
        faults.Fault("checkpoint_corrupt", at=0, count=100,
                     kind="corrupt"),
    ])
    with obs_bus.scope() as bus:
        with faults.install(plan):
            mgr.save(np.int64(20), 8)
    files = [os.path.basename(p) for p in mgr.list()]
    assert "ckpt-000000000004.npz" in files  # fallback survived
    state, pos, _, path = mgr.load_latest(like=np.int64(0))
    assert pos == 4 and int(state) == 10
    assert bus.snapshot()["counters"]["resilience.rotation_skipped"] == 1


def test_save_checkpoint_fsyncs_before_rename(tmp_path, monkeypatch):
    from gelly_tpu.engine.checkpoint import save_checkpoint

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: synced.append(fd))
    path = str(tmp_path / "c.npz")
    save_checkpoint(path, np.arange(4), position=1)
    assert len(synced) >= 1  # file fsync (dir fsync is best-effort)
    monkeypatch.setattr(os, "fsync", real_fsync)
    synced.clear()
    save_checkpoint(path, np.arange(4), position=2, fsync=False)
    assert synced == []


# ---------------------------------------------------------------------- #
# the cadenced path flatten


def _chain_depth_stream(n_pairs):
    """Edge chunks that force union_pairs_rooted chase depth to grow:
    stars are built high and their roots repeatedly hooked under ever
    smaller slots, so each union hangs a deep tree one level deeper."""
    from gelly_tpu.core.chunk import make_chunk

    edges = []
    for level in range(n_pairs - 1, -1, -1):
        a, b = 2 ** (level + 1), 2 ** level
        edges.append((a, b))
    return [
        make_chunk(np.array([a], np.int64), np.array([b], np.int64),
                   capacity=1, device=False)
        for a, b in edges
    ]


def test_flatten_state_bounds_chase_depth_bit_identical(tmp_path):
    """The regression the satellite names: depth after flatten <= 2,
    labels bit-identical with and without the cadenced flatten."""
    from gelly_tpu.ops import unionfind

    cap = 64
    chunks = _chain_depth_stream(5)  # depth ~5 without flattening

    fold = jax.jit(
        lambda p, c: unionfind.union_pairs_rooted(p, c.src, c.dst, c.valid)
    )
    step = lambda p, c: (fold(p, c), None)  # noqa: E731

    def run(flatten, ckpt_dir):
        r = ResilientRunner(
            step, chunks, lambda: unionfind.fresh_forest(cap),
            checkpoint_dir=ckpt_dir, config=_fast(),
            flatten_state=flatten,
        )
        return r, r.run()

    _, plain = run(None, None)
    assert unionfind.chase_depth(plain) > 2  # the test actually bites

    depths = []
    flat_fn = jax.jit(unionfind.pointer_jump)

    def spy_flatten(p):
        out = flat_fn(p)
        depths.append(unionfind.chase_depth(out))
        return out

    r2, flat = run(spy_flatten, str(tmp_path))
    assert depths and max(depths) <= 2
    assert unionfind.chase_depth(flat) <= 2
    # labels identical: flatten only shortcuts chains
    labels_a = np.asarray(jax.jit(unionfind.pointer_jump)(plain))
    labels_b = np.asarray(jax.jit(unionfind.pointer_jump)(flat))
    assert labels_a.tobytes() == labels_b.tobytes()
    # and the checkpoint on disk holds the flattened forest
    from gelly_tpu.engine.checkpoint import load_checkpoint

    state, _, _ = load_checkpoint(r2.manager.list()[-1])
    assert unionfind.chase_depth(state[0]) <= 2


def test_engine_flatten_at_checkpoint_cadence(tmp_path):
    """SummaryAggregation.flatten rides run_aggregation's checkpoint
    cadence: the snapshot holds a flattened forest and emissions are
    identical to a flatten-free run."""
    from gelly_tpu.engine.aggregation import (
        SummaryAggregation,
        run_aggregation,
    )
    from gelly_tpu.engine.checkpoint import load_checkpoint
    from gelly_tpu.ops import unionfind
    from gelly_tpu.parallel import mesh as mesh_lib

    cap = 64
    chunks = _chain_depth_stream(5)

    def mk_agg(flatten):
        return SummaryAggregation(
            init=lambda: unionfind.fresh_forest(cap),
            fold=lambda p, c: unionfind.union_pairs_rooted(
                p, c.src, c.dst, c.valid
            ),
            combine=unionfind.merge_forests,
            transform=None,
            fold_accumulates=True,
            flatten=flatten,
            name="chain-uf",
        )

    mesh = mesh_lib.make_mesh(1)  # accumulate plan: the depth-growing one
    plain = run_aggregation(
        mk_agg(None), list(chunks), mesh=mesh, merge_every=1,
    ).result()
    assert unionfind.chase_depth(plain) > 2

    ckpt = str(tmp_path / "flat.npz")
    flat = run_aggregation(
        mk_agg(lambda p: unionfind.pointer_jump(p)), list(chunks),
        mesh=mesh, merge_every=1, checkpoint_path=ckpt,
        checkpoint_every=2,
    ).result()
    state, _, _ = load_checkpoint(ckpt)
    assert unionfind.chase_depth(state[0]) <= 2
    labels_a = np.asarray(jax.jit(unionfind.pointer_jump)(plain))
    labels_b = np.asarray(jax.jit(unionfind.pointer_jump)(flat))
    assert labels_a.tobytes() == labels_b.tobytes()


def test_cc_plans_supply_flatten():
    from gelly_tpu.library.connected_components import (
        CCSummary,
        connected_components,
    )
    from gelly_tpu.ops import unionfind

    agg = connected_components(64)
    assert agg.flatten is not None
    deep = unionfind.fresh_forest(64).at[np.array([3, 2, 1])].set(
        np.array([2, 1, 0], np.int32)
    )
    flat = agg.flatten(CCSummary(parent=deep,
                                 seen=np.zeros(64, bool)))
    assert unionfind.chase_depth(flat.parent) <= 1
    compact = connected_components(1 << 21, codec="compact")
    assert compact.flatten is not None


# ---------------------------------------------------------------------- #
# host identity on heartbeat lines + exported traces


def test_heartbeat_lines_carry_host_identity(tmp_path):
    from gelly_tpu.obs.heartbeat import Heartbeat

    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    try:
        hb = Heartbeat(every_s=0)
        assert hb.tick(position=7)
        line = hb.lines[-1]
        assert line["process_index"] == 0
        assert line["process_count"] == 1
        assert "coordinator_address" in line
        assert line["leader"] is True  # active coordinator, sole host
        assert line["position"] == 7
    finally:
        co.close()
    hb2 = Heartbeat(every_s=0)
    assert hb2.tick(position=8)
    assert "leader" not in hb2.lines[-1]  # no coordinator active


def test_chrome_trace_otherdata_carries_host_identity(tmp_path):
    from gelly_tpu.obs.export import to_chrome_trace, validate_chrome_trace
    from gelly_tpu.obs.tracing import SpanTracer

    tr = SpanTracer(capacity=16)
    tr.span("fold", "fold", tr.now(), unit=0)
    co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
    try:
        trace = to_chrome_trace(tr)
    finally:
        co.close()
    validate_chrome_trace(trace)
    host = trace["otherData"]["host"]
    assert host["process_index"] == 0
    assert host["process_count"] == 1
    assert "coordinator_address" in host
    assert host["leader"] is True


# ---------------------------------------------------------------------- #
# concurrency regression (racecheck RC001/RC002 fix): LeaseBoard.beat is
# called from the background lease thread AND the protocol paths


@pytest.mark.racecheck
def test_lease_beat_concurrent_force_beats_lose_no_updates(tmp_path):
    """beat() races the lease thread against maybe_beat/barrier force
    beats; pre-fix the unlocked ``beats += 1`` lost updates and the
    rate-limit check-then-set admitted overlapping writes. Post-fix the
    counter is exact under contention."""
    store = CheckpointStore(str(tmp_path))
    board = LeaseBoard(store, host=0, ttl=5.0)
    n_threads, per_thread = 8, 25

    def hammer():
        for _ in range(per_thread):
            assert board.beat(force=True)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert board.beats == n_threads * per_thread
    # the lease file survived the concurrent atomic writes and is fresh
    assert board.wall(0) is not None
    assert not board.expired(0)


@pytest.mark.racecheck
def test_lease_beat_rate_limit_still_rate_limits(tmp_path):
    """The lock must not break the ttl/3 rate limit: unforced beats
    within the window are rejected without a write."""
    now = [100.0]
    board = LeaseBoard(CheckpointStore(str(tmp_path)), host=0, ttl=3.0,
                       clock=lambda: now[0])
    assert board.beat()            # first write
    assert not board.beat()        # inside ttl/3
    now[0] += 1.01                 # past ttl/3 = 1.0
    assert board.beat()
    assert board.beats == 2
