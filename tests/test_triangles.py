"""Triangle algorithms: ITCase parity, operator-level exactness, estimator
convergence (T/example/test/TriangleCountTest.java,
WindowTrianglesITCase.java + ExamplesTestData.java)."""

import numpy as np
import pytest

from gelly_tpu import TimeCharacteristic, edge_stream_from_edges
from gelly_tpu.library.triangles import (
    exact_triangle_count,
    sampled_triangle_count,
    window_triangles,
)

# ExamplesTestData.TRIANGLES_DATA: (src, dst, event-time ms)
TRIANGLES_DATA = [
    (1, 2, 100), (1, 3, 150), (3, 2, 200), (2, 4, 250), (3, 4, 300),
    (3, 5, 350), (4, 5, 400), (4, 6, 450), (6, 5, 500), (5, 7, 550),
    (6, 7, 600), (8, 6, 650), (7, 8, 700), (7, 9, 750), (8, 9, 800),
    (10, 8, 850), (9, 10, 900), (9, 11, 950), (10, 11, 1000),
]


def triangles_stream(chunk_size=4):
    return edge_stream_from_edges(
        [(s, d, float(t)) for s, d, t in TRIANGLES_DATA],
        vertex_capacity=32, chunk_size=chunk_size,
        time=TimeCharacteristic.EVENT,
        ts_fn=lambda s, d, v: v.astype(np.int64),
    )


def test_window_triangles_itcase_golden():
    # WindowTrianglesITCase: window 400ms -> counts {0: 2, 1: 3, 2: 2}
    # (golden "(2,399) (3,799) (2,1199)" as (count, window max ts)).
    s = triangles_stream()
    got = dict(window_triangles(s, 400))
    assert got == {0: 2, 1: 3, 2: 2}


def test_window_triangles_chunk_size_invariant():
    for cs in (1, 3, 19):
        got = dict(window_triangles(triangles_stream(cs), 400))
        assert got == {0: 2, 1: 3, 2: 2}, cs


def test_window_triangles_duplicate_edges_counted_once():
    edges = [(1, 2, 1.0), (2, 3, 2.0), (1, 3, 3.0), (1, 2, 4.0), (2, 1, 5.0)]
    s = edge_stream_from_edges(
        edges, vertex_capacity=8, chunk_size=2,
        time=TimeCharacteristic.EVENT, timestamps=np.array([0, 1, 2, 3, 4]),
    )
    assert dict(window_triangles(s, 1000)) == {0: 1}


def test_window_triangles_batched_groups_match_per_window():
    # The grouped-dispatch path (lax.map over stacked packed windows,
    # padded final group) must equal the per-window path for every batch
    # size, including batch > #windows and a partial final group.
    import jax.numpy as jnp

    from gelly_tpu.library.triangles import window_triangle_counts_batched

    want = {0: 2, 1: 3, 2: 2}
    for batch in (1, 2, 4, 8):
        wins, counts = zip(*window_triangle_counts_batched(
            triangles_stream(), 400, batch=batch
        ))
        got = dict(zip(wins, np.asarray(jnp.stack(counts)).tolist()))
        assert got == want, batch


def test_exact_triangle_count_full_graph():
    # All 19 edges, no windows: 9 triangles total
    # {1,2,3},{2,3,4},{3,4,5},{4,5,6},{5,6,7},{6,7,8},{7,8,9},{8,9,10},{9,10,11}
    s = triangles_stream()
    final = exact_triangle_count(s).final_counts()
    # ground truth via brute force
    import itertools

    adj = set()
    for a, b, _ in TRIANGLES_DATA:
        adj.add((a, b)); adj.add((b, a))
    verts = sorted({v for e in TRIANGLES_DATA for v in e[:2]})
    expected_total = sum(
        1 for a, b, c in itertools.combinations(verts, 3)
        if (a, b) in adj and (b, c) in adj and (a, c) in adj
    )
    assert final[-1] == expected_total
    # per-vertex counters: vertex participates in k triangles
    per_vertex = {
        v: sum(
            1 for a, b, c in itertools.combinations(verts, 3)
            if v in (a, b, c)
            and (a, b) in adj and (b, c) in adj and (a, c) in adj
        )
        for v in verts
    }
    per_vertex = {v: k for v, k in per_vertex.items() if k}
    assert {k: v for k, v in final.items() if k != -1} == per_vertex


def test_exact_triangle_order_and_chunking_invariant():
    rng = np.random.default_rng(11)
    for cs in (1, 5, 32):
        edges = [(s, d, float(t)) for s, d, t in TRIANGLES_DATA]
        perm = rng.permutation(len(edges))
        s = edge_stream_from_edges(
            [edges[i] for i in perm], vertex_capacity=32, chunk_size=cs
        )
        assert exact_triangle_count(s).final_counts()[-1] == 9


def test_exact_triangle_duplicates_are_noops():
    edges = [(1, 2), (2, 3), (1, 3), (1, 2), (3, 2), (1, 3)]
    s = edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=2)
    assert exact_triangle_count(s).final_counts()[-1] == 1


def test_sampled_estimator_unbiased_on_dense_graph():
    # Complete graph K12: T = C(12,3) = 220 triangles.
    import itertools

    verts = list(range(12))
    edges = [(a, b) for a, b in itertools.combinations(verts, 2)]
    rng = np.random.default_rng(5)
    rng.shuffle(edges)
    estimates = []
    for seed in range(8):
        s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=16)
        last = None
        for last in sampled_triangle_count(
            s, num_samples=512, num_vertices=12, seed=seed
        ):
            pass
        estimates.append(last)
    mean = float(np.mean(estimates))
    # Estimator is unbiased with variance ~T*V*E/S; allow a wide band.
    assert 220 * 0.4 < mean < 220 * 1.9, estimates


def test_sampled_estimator_zero_when_no_triangles():
    edges = [(i, i + 1) for i in range(30)]  # path: no triangles
    s = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=8)
    last = None
    for last in sampled_triangle_count(s, 256, num_vertices=31, seed=1):
        pass
    assert last == 0.0


def test_sampled_estimator_mesh_matches_single_device():
    # Instance axis sharded over the 8-device mesh (broadcast deployment,
    # BroadcastTriangleCount.java:41-45): per-instance key streams make the
    # estimate identical to the single-device layout, beta psum included.
    import itertools

    from gelly_tpu.parallel import mesh as mesh_lib

    verts = list(range(12))
    edges = [(a, b) for a, b in itertools.combinations(verts, 2)]

    def run(mesh):
        s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=16)
        return list(sampled_triangle_count(
            s, num_samples=256, num_vertices=12, seed=3, mesh=mesh
        ))

    single = run(None)
    sharded = run(mesh_lib.make_mesh(8))
    assert single == sharded
    assert run(mesh_lib.make_mesh(2)) == single


def test_sampled_estimator_skips_self_loops():
    # Self-loops close no wedge and must not enter the reservoir or the
    # edge count (they would skew the third-vertex draw past u == v).
    from gelly_tpu.core.chunk import make_chunk
    from gelly_tpu.library.triangles import _fresh_sampler, _sampler_step

    import jax.numpy as jnp

    src = np.array([1] * 40 + [1, 2, 1] + [2] * 10, np.int32)
    dst = np.array([1] * 40 + [2, 3, 3] + [2] * 10, np.int32)
    chunk = make_chunk(src, dst)
    state = _sampler_step(_fresh_sampler(128, seed=2), chunk, jnp.int32(4))
    # Only the 3 real edges count; no sampled pair is a self-loop.
    assert int(state.edge_count) == 3
    sampled = np.asarray(state.src) >= 0
    assert not (np.asarray(state.src)[sampled]
                == np.asarray(state.trg)[sampled]).any()


def test_exact_vectorized_matches_scan_reference():
    # The arrival-index slab step must agree with the literal per-edge scan
    # (IntersectNeighborhoods semantics) on totals AND per-vertex counts,
    # including duplicates, self-loops, and cross-chunk closing edges.
    from gelly_tpu.core.chunk import make_chunk
    from gelly_tpu.library.triangles import (
        _exact_step,
        _exact_step_scan,
        fresh_triangle_counts,
    )

    rng = np.random.default_rng(8)
    src = rng.integers(0, 24, 300).astype(np.int32)
    dst = rng.integers(0, 24, 300).astype(np.int32)
    a = fresh_triangle_counts(24)
    b = fresh_triangle_counts(24)
    for lo in range(0, 300, 64):
        chunk = make_chunk(src[lo:lo + 64], dst[lo:lo + 64], capacity=64)
        a = _exact_step(a, chunk)
        b = _exact_step_scan(b, chunk)
        assert int(a.total) == int(b.total)
    np.testing.assert_array_equal(np.asarray(a.counts), np.asarray(b.counts))
    np.testing.assert_array_equal(np.asarray(a.adj), np.asarray(b.adj))


def test_sampled_estimator_uses_live_vertex_count():
    # Default num_vertices follows the live table count, not the (much
    # larger) slot capacity — phantom third-vertex draws would make beta
    # nearly always 0 and the scale factor huge.
    import itertools

    verts = list(range(10))
    edges = [(a, b) for a, b in itertools.combinations(verts, 2)]

    s1 = edge_stream_from_edges(edges, vertex_capacity=1024, chunk_size=64)
    auto = list(sampled_triangle_count(s1, 256, seed=7))
    s2 = edge_stream_from_edges(edges, vertex_capacity=1024, chunk_size=64)
    explicit = list(sampled_triangle_count(s2, 256, num_vertices=10, seed=7))
    assert auto == explicit


def test_sparse_exact_matches_dense():
    # Capped-degree sparse path == dense arrival-index path, including
    # duplicates/self-loops across chunk boundaries.
    rng = np.random.default_rng(9)
    n_v, n_e = 64, 600
    edges = list(zip(rng.integers(0, n_v, n_e).tolist(),
                     rng.integers(0, n_v, n_e).tolist()))
    dense = exact_triangle_count(
        edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=64)
    ).final_counts()
    sparse = exact_triangle_count(
        edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=64),
        max_degree=n_v,
    ).final_counts()
    assert dense == sparse


def test_sparse_exact_million_vertex_capacity():
    # The VERDICT r1 gap: dense bool[N, N] capped N at ~10^4; the sparse
    # table runs at N = 1M with O(N * D) memory (~64MB at D = 8 vs 4TB
    # dense). Planted triangles spread across the id space.
    n_v = 1 << 20
    rng = np.random.default_rng(10)
    base = rng.choice(n_v, size=300, replace=False).astype(np.int64)
    edges = []
    for i in range(0, 300, 3):
        a, b, c = base[i], base[i + 1], base[i + 2]
        edges += [(a, b), (b, c), (a, c)]
    extra_u = rng.choice(n_v, 500).astype(np.int64)
    extra_v = rng.choice(n_v, 500).astype(np.int64)
    edges += list(zip(extra_u.tolist(), extra_v.tolist()))

    got = exact_triangle_count(
        edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=256),
        max_degree=8,
    ).final_counts()

    # Host oracle.
    adj: dict[int, set] = {}
    total = 0
    per: dict[int, int] = {}
    seen = set()
    for u, v in edges:
        u, v = int(u), int(v)
        if u == v or (min(u, v), max(u, v)) in seen:
            continue
        seen.add((min(u, v), max(u, v)))
        common = adj.get(u, set()) & adj.get(v, set())
        total += len(common)
        for w in common:
            per[w] = per.get(w, 0) + 1
        if common:
            per[u] = per.get(u, 0) + len(common)
            per[v] = per.get(v, 0) + len(common)
        adj.setdefault(u, set()).add(v)
        adj.setdefault(v, set()).add(u)
    want = {-1: total, **{k: v for k, v in per.items() if v}}
    assert got == want
    assert total >= 100  # the planted triangles actually exercised the path


def test_sparse_exact_degree_skew_raises():
    # A hot vertex past max_degree must raise (no silent wrong counts) —
    # the Twitter-skew discipline.
    edges = [(0, i) for i in range(1, 40)]
    s = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=8)
    with pytest.raises(ValueError, match="max_degree"):
        exact_triangle_count(s, max_degree=8).final_counts()


def test_window_triangles_mxu_kernel_matches_gather():
    # Pallas MXU wedge-matrix path (interpret mode on CPU) == VPU gather path.
    s = edge_stream_from_edges(
        [(s_, d, float(t)) for s_, d, t in TRIANGLES_DATA],
        vertex_capacity=128, chunk_size=4, time=TimeCharacteristic.EVENT,
        ts_fn=lambda a, b, v: v.astype(np.int64),
    )
    got = dict(window_triangles(s, 400, method="mxu_interpret"))
    assert got == {0: 2, 1: 3, 2: 2}


def test_wedge_count_matrix_random():
    import jax.numpy as jnp

    from gelly_tpu.ops.pallas_kernels import wedge_count_matrix

    rng = np.random.default_rng(0)
    m = jnp.asarray(rng.random((256, 256)) < 0.1)
    w = wedge_count_matrix(m, interpret=True)
    expected = np.asarray(m, np.float32).T @ np.asarray(m, np.float32)
    np.testing.assert_allclose(np.asarray(w), expected)


def test_window_triangles_sparse_matches_dense():
    # The capped-degree sparse window kernel (the large-N path) must agree
    # with the dense kernel on any stream, including duplicate edges,
    # reversed duplicates, and self-loops; across batch groupings.
    from gelly_tpu.library.triangles import window_triangle_counts_batched

    import jax.numpy as jnp

    rng = np.random.default_rng(27)
    n_v = 128
    n_e = 3000
    src = rng.integers(0, n_v, n_e)
    dst = rng.integers(0, n_v, n_e)
    ts = np.arange(n_e, dtype=np.int64)

    def stream():
        return edge_stream_from_edges(
            [(int(a), int(b), 1.0) for a, b in zip(src, dst)],
            vertex_capacity=n_v, chunk_size=512,
            time=TimeCharacteristic.EVENT, timestamps=ts,
        )

    def run(**kw):
        wins, counts = zip(*window_triangle_counts_batched(
            stream(), n_e // 5, **kw
        ))
        return dict(zip(wins, np.asarray(jnp.stack(counts)).tolist()))

    dense = run()
    for batch in (1, 4):
        assert run(max_degree=n_v, batch=batch) == dense, batch


def test_window_triangles_sparse_overflow_raises():
    from gelly_tpu.library.triangles import window_triangle_counts_batched

    # A star vertex with degree > max_degree must raise, not undercount.
    edges = [(0, i, 1.0) for i in range(1, 20)]
    s = edge_stream_from_edges(
        edges, vertex_capacity=64, chunk_size=32,
        time=TimeCharacteristic.EVENT,
        timestamps=np.arange(len(edges), dtype=np.int64),
    )
    with pytest.raises(ValueError, match="max_degree"):
        list(window_triangle_counts_batched(s, 1000, max_degree=4))


def test_window_triangles_bucketed_matches_dense():
    # The degree-bucketed sparse path (large-N workhorse) must agree with
    # the dense kernel on duplicate edges, reversed duplicates, and
    # self-loops, across batch groupings and skew (Zipf hot vertices now
    # work without a toy degree cap).
    import jax.numpy as jnp

    from gelly_tpu.library.triangles import (
        window_triangle_counts_batched,
        window_triangles_bucketed,
    )

    rng = np.random.default_rng(41)
    n_v = 128
    n_e = 3000
    src = rng.zipf(1.5, n_e) % n_v
    dst = rng.zipf(1.5, n_e) % n_v
    ts = np.arange(n_e, dtype=np.int64)

    def stream():
        return edge_stream_from_edges(
            [(int(a), int(b), 1.0) for a, b in zip(src, dst)],
            vertex_capacity=n_v, chunk_size=512,
            time=TimeCharacteristic.EVENT, timestamps=ts,
        )

    wins, counts = zip(*window_triangle_counts_batched(stream(), n_e // 5))
    dense = dict(zip(wins, np.asarray(jnp.stack(counts)).tolist()))
    for batch in (1, 3, 8):
        wins_b, counts_b = zip(*window_triangles_bucketed(
            stream(), n_e // 5, batch=batch
        ))
        got = dict(zip(wins_b, np.asarray(jnp.stack(counts_b)).tolist()))
        assert got == dense, batch


def test_window_triangles_bucketed_cap_raises_before_yield():
    from gelly_tpu.library.triangles import window_triangles_bucketed

    star = [(0, i, 1.0) for i in range(1, 20)]
    s = edge_stream_from_edges(
        star, vertex_capacity=64, chunk_size=32,
        time=TimeCharacteristic.EVENT,
        timestamps=np.zeros(len(star), dtype=np.int64),
    )
    it = window_triangles_bucketed(s, 1000, max_degree=4)
    with pytest.raises(ValueError, match="max_degree"):
        next(it)  # raises BEFORE any (possibly corrupt) count is yielded


def test_window_triangles_bucketed_million_vertex():
    from gelly_tpu.library.triangles import window_triangles_bucketed

    n_v = 1 << 20
    # Two triangles far apart in a million-slot space + noise edges.
    edges = [(10, 999_000, 1.0), (999_000, 500_000, 1.0),
             (500_000, 10, 1.0),
             (7, 8, 1.0), (8, 9, 1.0), (9, 7, 1.0),
             (1, 2, 1.0), (3, 4, 1.0)]
    s = edge_stream_from_edges(
        edges, vertex_capacity=n_v, chunk_size=8,
        time=TimeCharacteristic.EVENT,
        timestamps=np.zeros(len(edges), dtype=np.int64),
    )
    out = list(window_triangles_bucketed(s, 1000))
    assert len(out) == 1 and int(out[0][1]) == 2


def test_window_triangles_sparse_yield_overflow():
    from gelly_tpu.library.triangles import window_triangle_counts_batched

    # yield_overflow=True surfaces the per-window overflow scalar so
    # per-yield consumers can gate programmatically (ADVICE r3): clean
    # windows report 0, an overflowing window reports its dropped-entry
    # count in the SAME yielded tuple (before the deferred raise fires).
    edges = [(0, 1, 1.0), (1, 2, 1.0), (0, 2, 1.0)]  # one clean triangle
    s = edge_stream_from_edges(
        edges, vertex_capacity=64, chunk_size=32,
        time=TimeCharacteristic.EVENT,
        timestamps=np.zeros(len(edges), dtype=np.int64),
    )
    out = list(window_triangle_counts_batched(
        s, 1000, max_degree=4, yield_overflow=True
    ))
    assert len(out) == 1
    w, count, over = out[0]
    assert int(count) == 1 and int(over) == 0

    star = [(0, i, 1.0) for i in range(1, 20)]  # degree 19 > max_degree 4
    s = edge_stream_from_edges(
        star, vertex_capacity=64, chunk_size=32,
        time=TimeCharacteristic.EVENT,
        timestamps=np.zeros(len(star), dtype=np.int64),
    )
    it = window_triangle_counts_batched(
        s, 1000, max_degree=4, yield_overflow=True
    )
    w, count, over = next(it)
    assert int(over) > 0  # corrupt window flagged in-band
    with pytest.raises(ValueError, match="max_degree"):
        list(it)  # the deferred guard still fires


def test_window_triangles_sparse_million_vertex_capacity():
    # The whole point of the sparse kernel: vertex capacity where the
    # dense bool[N, N] adjacency (and the packed i32 format) cannot exist.
    from gelly_tpu.library.triangles import window_triangles

    n_v = 1 << 20
    rng = np.random.default_rng(35)
    ids = rng.choice(n_v, 9, replace=False).tolist()
    a, b, c, d, e, f, g, h, i = ids
    edges = [
        # window 0: one triangle + a chord pair
        (a, b, 1.0), (b, c, 1.0), (c, a, 1.0), (d, e, 1.0),
        # window 1: two triangles sharing edge (f, g)
        (f, g, 1.0), (g, h, 1.0), (h, f, 1.0), (g, i, 1.0), (i, f, 1.0),
    ]
    ts = np.array([0, 1, 2, 3, 10, 11, 12, 13, 14], dtype=np.int64)
    s = edge_stream_from_edges(
        edges, vertex_capacity=n_v, chunk_size=4,
        time=TimeCharacteristic.EVENT, timestamps=ts,
    )
    got = dict(window_triangles(s, 10, max_degree=8))
    assert got == {0: 1, 1: 2}


def test_arrival_rebase_lossless():
    # VERDICT r2 item 8: streaming past the i32 arrival budget must rebase
    # the summary losslessly instead of raising. A tiny budget mocks the
    # 2^31 counter; counts must match the unbounded run exactly, with
    # cross-chunk duplicates in the stream (the dedup interacts with
    # rebased indices).
    from gelly_tpu.library.triangles import exact_triangle_count

    rng = np.random.default_rng(21)
    n_e = 600
    src = rng.integers(0, 64, n_e).astype(np.int64)
    dst = rng.integers(0, 64, n_e).astype(np.int64)
    src[200:300] = src[:100]  # duplicates spanning future rebases
    dst[200:300] = dst[:100]

    def stream():
        return edge_stream_from_edges(
            list(zip(src.tolist(), dst.tolist())),
            vertex_capacity=64, chunk_size=64,
        )

    base = exact_triangle_count(stream()).final_counts()
    assert base[-1] > 0
    for budget in (130, 200, 400):
        t = exact_triangle_count(stream(), arrival_budget=budget)
        assert t.final_counts() == base, budget
        assert t.stats["rebases"] > 0, budget
    # Sparse path: same contract.
    t = exact_triangle_count(stream(), max_degree=64, arrival_budget=200)
    assert t.final_counts() == base
    assert t.stats["rebases"] > 0
