"""Triangle algorithms: ITCase parity, operator-level exactness, estimator
convergence (T/example/test/TriangleCountTest.java,
WindowTrianglesITCase.java + ExamplesTestData.java)."""

import numpy as np
import pytest

from gelly_tpu import TimeCharacteristic, edge_stream_from_edges
from gelly_tpu.library.triangles import (
    exact_triangle_count,
    sampled_triangle_count,
    window_triangles,
)

# ExamplesTestData.TRIANGLES_DATA: (src, dst, event-time ms)
TRIANGLES_DATA = [
    (1, 2, 100), (1, 3, 150), (3, 2, 200), (2, 4, 250), (3, 4, 300),
    (3, 5, 350), (4, 5, 400), (4, 6, 450), (6, 5, 500), (5, 7, 550),
    (6, 7, 600), (8, 6, 650), (7, 8, 700), (7, 9, 750), (8, 9, 800),
    (10, 8, 850), (9, 10, 900), (9, 11, 950), (10, 11, 1000),
]


def triangles_stream(chunk_size=4):
    return edge_stream_from_edges(
        [(s, d, float(t)) for s, d, t in TRIANGLES_DATA],
        vertex_capacity=32, chunk_size=chunk_size,
        time=TimeCharacteristic.EVENT,
        ts_fn=lambda s, d, v: v.astype(np.int64),
    )


def test_window_triangles_itcase_golden():
    # WindowTrianglesITCase: window 400ms -> counts {0: 2, 1: 3, 2: 2}
    # (golden "(2,399) (3,799) (2,1199)" as (count, window max ts)).
    s = triangles_stream()
    got = dict(window_triangles(s, 400))
    assert got == {0: 2, 1: 3, 2: 2}


def test_window_triangles_chunk_size_invariant():
    for cs in (1, 3, 19):
        got = dict(window_triangles(triangles_stream(cs), 400))
        assert got == {0: 2, 1: 3, 2: 2}, cs


def test_window_triangles_duplicate_edges_counted_once():
    edges = [(1, 2, 1.0), (2, 3, 2.0), (1, 3, 3.0), (1, 2, 4.0), (2, 1, 5.0)]
    s = edge_stream_from_edges(
        edges, vertex_capacity=8, chunk_size=2,
        time=TimeCharacteristic.EVENT, timestamps=np.array([0, 1, 2, 3, 4]),
    )
    assert dict(window_triangles(s, 1000)) == {0: 1}


def test_exact_triangle_count_full_graph():
    # All 19 edges, no windows: 9 triangles total
    # {1,2,3},{2,3,4},{3,4,5},{4,5,6},{5,6,7},{6,7,8},{7,8,9},{8,9,10},{9,10,11}
    s = triangles_stream()
    final = exact_triangle_count(s).final_counts()
    # ground truth via brute force
    import itertools

    adj = set()
    for a, b, _ in TRIANGLES_DATA:
        adj.add((a, b)); adj.add((b, a))
    verts = sorted({v for e in TRIANGLES_DATA for v in e[:2]})
    expected_total = sum(
        1 for a, b, c in itertools.combinations(verts, 3)
        if (a, b) in adj and (b, c) in adj and (a, c) in adj
    )
    assert final[-1] == expected_total
    # per-vertex counters: vertex participates in k triangles
    per_vertex = {
        v: sum(
            1 for a, b, c in itertools.combinations(verts, 3)
            if v in (a, b, c)
            and (a, b) in adj and (b, c) in adj and (a, c) in adj
        )
        for v in verts
    }
    per_vertex = {v: k for v, k in per_vertex.items() if k}
    assert {k: v for k, v in final.items() if k != -1} == per_vertex


def test_exact_triangle_order_and_chunking_invariant():
    rng = np.random.default_rng(11)
    for cs in (1, 5, 32):
        edges = [(s, d, float(t)) for s, d, t in TRIANGLES_DATA]
        perm = rng.permutation(len(edges))
        s = edge_stream_from_edges(
            [edges[i] for i in perm], vertex_capacity=32, chunk_size=cs
        )
        assert exact_triangle_count(s).final_counts()[-1] == 9


def test_exact_triangle_duplicates_are_noops():
    edges = [(1, 2), (2, 3), (1, 3), (1, 2), (3, 2), (1, 3)]
    s = edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=2)
    assert exact_triangle_count(s).final_counts()[-1] == 1


def test_sampled_estimator_unbiased_on_dense_graph():
    # Complete graph K12: T = C(12,3) = 220 triangles.
    import itertools

    verts = list(range(12))
    edges = [(a, b) for a, b in itertools.combinations(verts, 2)]
    rng = np.random.default_rng(5)
    rng.shuffle(edges)
    estimates = []
    for seed in range(8):
        s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=16)
        last = None
        for last in sampled_triangle_count(
            s, num_samples=512, num_vertices=12, seed=seed
        ):
            pass
        estimates.append(last)
    mean = float(np.mean(estimates))
    # Estimator is unbiased with variance ~T*V*E/S; allow a wide band.
    assert 220 * 0.4 < mean < 220 * 1.9, estimates


def test_sampled_estimator_zero_when_no_triangles():
    edges = [(i, i + 1) for i in range(30)]  # path: no triangles
    s = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=8)
    last = None
    for last in sampled_triangle_count(s, 256, num_vertices=31, seed=1):
        pass
    assert last == 0.0


def test_window_triangles_mxu_kernel_matches_gather():
    # Pallas MXU wedge-matrix path (interpret mode on CPU) == VPU gather path.
    s = edge_stream_from_edges(
        [(s_, d, float(t)) for s_, d, t in TRIANGLES_DATA],
        vertex_capacity=128, chunk_size=4, time=TimeCharacteristic.EVENT,
        ts_fn=lambda a, b, v: v.astype(np.int64),
    )
    got = dict(window_triangles(s, 400, method="mxu_interpret"))
    assert got == {0: 2, 1: 3, 2: 2}


def test_wedge_count_matrix_random():
    import jax.numpy as jnp

    from gelly_tpu.ops.pallas_kernels import wedge_count_matrix

    rng = np.random.default_rng(0)
    m = jnp.asarray(rng.random((256, 256)) < 0.1)
    w = wedge_count_matrix(m, interpret=True)
    expected = np.asarray(m, np.float32).T @ np.asarray(m, np.float32)
    np.testing.assert_allclose(np.asarray(w), expected)
