"""Fused multi-query execution (``gelly_tpu/engine/multiquery.py``).

The acceptance contract: every library plan folded FUSED produces
summaries bit-identical to its standalone run on adversarial streams
(hot vertex, self-loops, odd cycles), one fold dispatch advances all Q
queries per chunk, un-fusable plans are refused loudly, the fused
checkpoint (one position, every query's leaves in one file) resumes
exactly-once — including under SIGKILL with units in flight (crash
child) — and live per-query snapshots answer with a one-window
staleness bound.
"""

import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine.aggregation import (
    SummaryAggregation,
    run_aggregation,
)
from gelly_tpu.engine.multiquery import (
    MultiQueryPlan,
    MultiQueryStream,
    QuerySpec,
    fuse,
    run_multiquery,
)
from gelly_tpu.library.bipartiteness import bipartiteness_query
from gelly_tpu.library.connected_components import (
    cc_query,
    connected_components,
)
from gelly_tpu.library.degrees import degrees_query
from gelly_tpu.library.spanner import spanner_query
from gelly_tpu.obs import bus as obs_bus
from gelly_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.multiquery

N_V = 96
CHUNK = 32


def _adversarial_edges():
    """Hot vertex + self-loops + an odd cycle (bipartiteness's hard
    case) + an even cycle + random pairs; slots >= 90 stay unseen."""
    edges = [(1, 2), (2, 3), (3, 1)]  # odd cycle
    edges += [(4, 5), (5, 6), (6, 7), (7, 4)]  # even cycle
    edges += [(0, 0), (9, 9)]  # self-loops
    edges += [(0, v) for v in range(20, 44)]  # hot vertex 0
    rng = np.random.default_rng(41)
    edges += [(int(a), int(b)) for a, b in rng.integers(10, 90, (96, 2))]
    return edges


def _stream(edges=None, chunk=CHUNK):
    return edge_stream_from_edges(
        edges if edges is not None else _adversarial_edges(),
        vertex_capacity=N_V, chunk_size=chunk,
    )


def _mesh1():
    return mesh_lib.make_mesh(1)


def _kw(**over):
    kw = dict(mesh=_mesh1(), ingest_workers=0, prefetch_depth=0,
              h2d_depth=0)
    kw.update(over)
    return kw


def _quartet():
    return [
        cc_query(N_V),
        degrees_query(N_V),
        bipartiteness_query(N_V),
        spanner_query(N_V, k=2, every=2),
    ]


def _assert_tree_identical(want, got, label):
    wl, gl = jax.tree.leaves(want), jax.tree.leaves(got)
    assert len(wl) == len(gl), label
    for w, g in zip(wl, gl):
        w, g = np.asarray(w), np.asarray(g)
        assert w.dtype == g.dtype, (label, w.dtype, g.dtype)
        assert w.shape == g.shape, (label, w.shape, g.shape)
        assert w.tobytes() == g.tobytes(), f"{label}: summary diverged"


def _dummy_agg(**over):
    kw = dict(
        init=lambda: jnp.zeros((4,), jnp.int32),
        fold=lambda s, c: s,
        combine=lambda a, b: a + b,
        name="dummy",
    )
    kw.update(over)
    return SummaryAggregation(**kw)


# --------------------------------------------------------------------- #
# fused-vs-standalone parity (the library quartet)


def test_fused_quartet_bit_identical_to_standalone():
    """All four library plans fused into ONE plan over the adversarial
    stream: every per-query summary bit-identical to that plan's
    standalone run (the spanner's every=2 merge window matching the
    standalone run's merge_every=2)."""
    queries = _quartet()
    fused_final = run_aggregation(
        None, _stream(), queries=queries, merge_every=2, **_kw()
    ).result()
    assert sorted(fused_final) == [
        "bipartiteness", "cc", "degrees", "spanner",
    ]
    for q in queries:
        want = run_aggregation(
            q.agg, _stream(), merge_every=2, **_kw()
        ).result()
        _assert_tree_identical(want, fused_final[q.name], q.name)


def test_fused_emission_matches_every_window_not_just_final():
    """Window-by-window parity: the fused emission stream yields the
    same per-query values at every close as the standalone runs."""
    queries = [cc_query(N_V), degrees_query(N_V)]
    fused = list(run_aggregation(
        None, _stream(), queries=queries, merge_every=2, **_kw()
    ))
    for q in queries:
        alone = list(run_aggregation(
            q.agg, _stream(), merge_every=2, **_kw()
        ))
        assert len(alone) == len(fused)
        for i, (w, f) in enumerate(zip(alone, fused)):
            _assert_tree_identical(w, f[q.name], f"{q.name}@{i}")


def test_per_query_merge_window_decouples_from_engine_cadence():
    """A non-accum query's merge window (every=2) fires at its own
    chunk cadence regardless of the engine's emission cadence: fused
    at merge_every=1 still matches standalone at merge_every=2."""
    sp = spanner_query(N_V, k=2, every=2)
    fused_final = run_aggregation(
        None, _stream(), queries=[cc_query(N_V), sp], merge_every=1,
        **_kw()
    ).result()
    want = run_aggregation(
        sp.agg, _stream(), merge_every=2, **_kw()
    ).result()
    _assert_tree_identical(want, fused_final["spanner"], "spanner")


def test_fused_accumulating_queries_ride_a_sharded_mesh():
    """All-accumulating fused plans (every=1) are admitted at S > 1 and
    stay bit-identical to their standalone sharded runs."""
    queries = [cc_query(N_V), degrees_query(N_V)]
    m = mesh_lib.make_mesh()  # the conftest 8-virtual-device mesh
    fused_final = run_aggregation(
        None, _stream(), queries=queries, merge_every=2, mesh=m,
        ingest_workers=0, prefetch_depth=0, h2d_depth=0,
    ).result()
    for q in queries:
        want = run_aggregation(
            q.agg, _stream(), merge_every=2, mesh=m,
            ingest_workers=0, prefetch_depth=0, h2d_depth=0,
        ).result()
        _assert_tree_identical(want, fused_final[q.name], q.name)


def test_fused_through_the_sharded_source_provider(tmp_path):
    """The fused plan rides the sharded byte-range readers: one staging
    pass per chunk feeds every query, parity with the inline run."""
    from gelly_tpu.ingest import edge_stream_from_sharded_file

    path = tmp_path / "edges.txt"
    path.write_text(
        "".join(f"{a} {b}\n" for a, b in _adversarial_edges())
    )
    def provider_stream():
        return edge_stream_from_sharded_file(
            str(path), vertex_capacity=N_V, shards=2, chunk_size=CHUNK,
        )

    queries = [cc_query(N_V), degrees_query(N_V)]
    fused_final = run_aggregation(
        None, provider_stream(), queries=queries, merge_every=2,
        mesh=_mesh1(), source_provider=True,
    ).result()
    # Parity against each query's STANDALONE run through the same
    # provider (the reader lanes' chunking differs from the inline
    # stream's, so the oracle must share it).
    for q in queries:
        want = run_aggregation(
            q.agg, provider_stream(), merge_every=2,
            mesh=_mesh1(), source_provider=True,
        ).result()
        _assert_tree_identical(want, fused_final[q.name], q.name)


# --------------------------------------------------------------------- #
# fusion eligibility / refusals


def test_fuse_refuses_stateful_codec_plans():
    """Only the genuinely unfusable cases stay refused now that plain
    codec plans ride the shared compress stage — and the message says
    WHY: the stack_ordered session's id assignment needs global stream
    order, and a requires_codec plan without an engageable shared
    codec has no raw fold to fall back to."""
    compact = connected_components(N_V, codec="compact",
                                   compact_capacity=N_V)
    with pytest.raises(ValueError, match="GLOBAL STREAM order"):
        fuse([cc_query(N_V), QuerySpec("compact", compact)])
    with pytest.raises(ValueError, match="stack_ordered"):
        fuse([QuerySpec("ordered", _dummy_agg(stack_ordered=True))])
    with pytest.raises(ValueError, match="raw fold does not exist"):
        fuse([QuerySpec("codec", _dummy_agg(requires_codec=True))])


def test_fuse_refuses_transient_and_host_transforms():
    with pytest.raises(ValueError, match="transient"):
        fuse([QuerySpec("t", _dummy_agg(transient=True))])
    with pytest.raises(ValueError, match="host-side"):
        fuse([QuerySpec("h", _dummy_agg(transform=lambda s: s,
                                        jit_transform=False))])


def test_fuse_refuses_mismatched_chunk_schemas():
    with pytest.raises(ValueError, match="mismatched chunk schemas"):
        fuse([cc_query(64), degrees_query(128)])


def test_fuse_refuses_bad_names_windows_and_nesting():
    with pytest.raises(ValueError, match="at least one"):
        fuse([])
    with pytest.raises(ValueError, match="duplicate"):
        fuse([cc_query(N_V), cc_query(N_V)])
    with pytest.raises(ValueError, match="reserved"):
        fuse([QuerySpec("_step", _dummy_agg())])
    with pytest.raises(ValueError, match="every"):
        fuse([QuerySpec("s", _dummy_agg(), every=0)])
    # an accumulating plan has no merge window to defer
    with pytest.raises(ValueError, match="accumulates"):
        fuse([QuerySpec("acc", _dummy_agg(fold_accumulates=True),
                        every=2)])
    inner = fuse([cc_query(N_V)])
    with pytest.raises(ValueError, match="nesting"):
        fuse([QuerySpec("outer", inner)])


def test_run_aggregation_fused_arg_validation():
    with pytest.raises(ValueError, match="not both"):
        run_aggregation(_dummy_agg(), _stream(),
                        queries=[cc_query(N_V)], **_kw())
    with pytest.raises(ValueError, match="required"):
        run_aggregation(None, _stream(), **_kw())
    with pytest.raises(ValueError, match="merge_every-only"):
        run_aggregation(None, _stream(), queries=[cc_query(N_V)],
                        window_ms=100, **_kw())
    with pytest.raises(ValueError, match="host_precombine"):
        run_aggregation(None, _stream(), queries=[cc_query(N_V)],
                        host_precombine=lambda c: c, **_kw())
    # non-accum queries (in-fold merges are per-partition) refuse S > 1
    with pytest.raises(ValueError, match="single-shard"):
        run_aggregation(
            None, _stream(),
            queries=[cc_query(N_V), spanner_query(N_V, k=2)],
            merge_every=2, mesh=mesh_lib.make_mesh(),
            ingest_workers=0, prefetch_depth=0, h2d_depth=0,
        )


# --------------------------------------------------------------------- #
# fused codec sharing (the shared compression plane)


def _codec_queries():
    return [
        cc_query(N_V, compressed=True, codec="sparse"),
        degrees_query(N_V, compressed=True, codec="sparse"),
        bipartiteness_query(N_V, compressed=True, codec="sparse"),
    ]


def _bipartite_adversarial_edges():
    """The adversarial shapes minus odd cycles/self-loops (hot vertex,
    even cycle, random even->odd pairs): keeps the bipartiteness
    labels/colors DEFINED, so raw-vs-codec window comparisons are exact
    on every leaf (after a conflict the forest internals are
    implementation-defined — the observable collapses to the ok flag,
    which the standalone-vs-fused comparison below still covers)."""
    edges = [(4, 5), (5, 6), (6, 7), (7, 4)]  # even cycle
    edges += [(0, v) for v in range(21, 44, 2)]  # hot vertex (even->odd)
    rng = np.random.default_rng(43)
    a = rng.integers(5, 44, 64) * 2
    b = rng.integers(5, 44, 64) * 2 + 1
    edges += [(int(x), int(y)) for x, y in zip(a, b)]
    return edges


def test_fused_codec_one_payload_window_parity():
    """With every query's codec on, the fused plan compresses each
    chunk ONCE — one multi-query payload (compress spans == chunks,
    not chunks x Q; ``multiquery.compressed_chunks`` == chunks) — and
    the run is window-by-window bit-identical to the raw fused run."""
    from gelly_tpu import obs

    edges = _bipartite_adversarial_edges()
    n_chunks = -(-len(edges) // CHUNK)
    raw = list(run_aggregation(
        None, _stream(edges),
        queries=[cc_query(N_V), degrees_query(N_V),
                 bipartiteness_query(N_V)],
        merge_every=2, **_kw(),
    ))
    tracer = obs.SpanTracer()
    with obs_bus.scope() as bus, obs.install(tracer):
        comp = list(run_aggregation(
            None, _stream(edges), queries=_codec_queries(),
            merge_every=2, **_kw(),
        ))
    assert len(raw) == len(comp) >= 2
    for i, (a, b) in enumerate(zip(raw, comp)):
        for name in ("cc", "degrees", "bipartiteness"):
            _assert_tree_identical(a[name], b[name], f"w{i}/{name}")
    counters = bus.snapshot()["counters"]
    assert counters["multiquery.compressed_chunks"] == n_chunks
    assert len(tracer.spans("compress")) == n_chunks
    assert len(tracer.spans("fold")) == n_chunks  # still 1/chunk


def test_fused_codec_matches_standalone_codec_runs():
    """Fused-codec vs STANDALONE codec runs on the full adversarial
    stream: every query's final summary bit-identical — both sides run
    the same fold_compressed over the same per-query stacked payloads,
    so even conflict-collapsed forests match exactly."""
    final = run_aggregation(
        None, _stream(), queries=_codec_queries(), merge_every=2,
        **_kw(),
    ).result()
    for q in _codec_queries():
        alone = run_aggregation(
            q.agg, _stream(), merge_every=2, **_kw()
        ).result()
        _assert_tree_identical(alone, final[q.name], q.name)


def test_fuse_share_codec_knob():
    fused = fuse(_codec_queries(), share_codec=True)
    assert fused.host_compress is not None
    assert fused.fold_compressed is not None
    pinned_raw = fuse(_codec_queries(), share_codec=False)
    assert pinned_raw.host_compress is None
    # mixed sets (one raw query) fall back to the raw fused fold
    mixed = fuse([cc_query(N_V, compressed=True, codec="sparse"),
                  degrees_query(N_V)])
    assert mixed.host_compress is None
    # a non-accumulating query keeps the codec off (its masked merge
    # window fires at chunk grain inside the raw fold)
    with pytest.raises(ValueError, match="share_codec=True"):
        fuse([cc_query(N_V, compressed=True, codec="sparse"),
              spanner_query(N_V, k=2, every=2)], share_codec=True)
    with pytest.raises(ValueError, match="share_codec"):
        fuse(_codec_queries(), share_codec="yes")


def test_fused_codec_checkpoint_resume_bit_identical(tmp_path):
    """The codec-path twin of the raw resume test: one position covers
    every query's leaves + the step counter; a mid-stream resume of
    the fused-CODEC run finishes bit-identical."""
    golden = run_aggregation(
        None, _stream(), queries=_codec_queries(), merge_every=2,
        **_kw(),
    ).result()
    ck = str(tmp_path / "mqc.npz")
    it = iter(run_aggregation(
        None, _stream(), queries=_codec_queries(), merge_every=2,
        checkpoint_path=ck, checkpoint_every=1, **_kw(),
    ))
    next(it)
    next(it)
    it.close()
    assert os.path.exists(ck)
    from gelly_tpu.engine.checkpoint import read_checkpoint_header

    pos = read_checkpoint_header(ck)["position"]
    assert 0 < pos < len(list(_stream()))
    resumed = run_aggregation(
        None, _stream(), queries=_codec_queries(), merge_every=2,
        checkpoint_path=ck, checkpoint_every=1, resume=True, **_kw(),
    ).result()
    for name in ("cc", "degrees", "bipartiteness"):
        _assert_tree_identical(golden[name], resumed[name], name)


# --------------------------------------------------------------------- #
# exactly-once checkpoint / resume


def test_fused_checkpoint_resume_bit_identical(tmp_path):
    """One position + every query's leaves (including the step counter
    driving the spanner's merge window) in one checkpoint: an
    interrupted fused run resumed mid-stream finishes bit-identical to
    the uninterrupted run."""
    queries = [cc_query(N_V), spanner_query(N_V, k=2, every=2)]
    golden = run_aggregation(
        None, _stream(), queries=queries, merge_every=2, **_kw()
    ).result()
    ck = str(tmp_path / "mq.npz")
    it = iter(run_aggregation(
        None, _stream(), queries=queries, merge_every=2,
        checkpoint_path=ck, checkpoint_every=1, **_kw()
    ))
    next(it)
    next(it)  # the window-1 checkpoint lands when the generator resumes
    it.close()
    assert os.path.exists(ck)
    from gelly_tpu.engine.checkpoint import read_checkpoint_header

    pos = read_checkpoint_header(ck)["position"]
    assert 0 < pos < len(list(_stream()))  # genuinely mid-stream
    resumed = run_aggregation(
        None, _stream(), queries=queries, merge_every=2,
        checkpoint_path=ck, checkpoint_every=1, resume=True, **_kw()
    ).result()
    for name in ("cc", "spanner"):
        _assert_tree_identical(golden[name], resumed[name], name)


CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_multiquery_crash_child.py")


def _spawn(ckpt, out, sleep_s, compressed=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single default CPU device is enough
    if compressed:
        env["GELLY_MQ_COMPRESSED"] = "1"
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(out), str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.parametrize("compressed", [False, True],
                         ids=["raw", "codec"])
def test_fused_kill9_resume_bit_identical(tmp_path, compressed):
    """SIGKILL with units in flight: the resumed fused run's per-query
    emissions are bit-identical to an unkilled run — the one recorded
    position covers every query at once. The ``codec`` variant runs
    the fused-CODEC plan (shared compress stage + fold_compressed), so
    the kill lands with compressed payload units in flight."""
    from gelly_tpu.engine.checkpoint import load_checkpoint

    ckpt = tmp_path / "mq-ck.npz"
    out_clean = tmp_path / "clean.npz"
    out_resumed = tmp_path / "resumed.npz"

    p = _spawn(tmp_path / "clean-ck.npz", out_clean, 0.0,
               compressed=compressed)
    assert p.wait(timeout=300) == 0

    p = _spawn(ckpt, out_resumed, 0.05, compressed=compressed)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if p.poll() is not None:
            pytest.fail(f"child exited early (rc={p.returncode})")
        if ckpt.exists():
            break
        time.sleep(0.02)
    else:
        pytest.fail("no checkpoint appeared before the deadline")
    os.kill(p.pid, signal.SIGKILL)
    assert p.wait(timeout=60) == -signal.SIGKILL
    assert not out_resumed.exists()

    _, pos, _ = load_checkpoint(str(ckpt))
    import _multiquery_crash_child as child

    total = -(-child.N_EDGES // child.CHUNK)
    assert 0 < pos < total  # mid-stream position

    p = _spawn(ckpt, out_resumed, 0.0, compressed=compressed)
    assert p.wait(timeout=300) == 0
    resumed, _, _ = load_checkpoint(str(out_resumed))
    clean, _, _ = load_checkpoint(str(out_clean))
    assert len(resumed) == len(clean)
    for r, c in zip(resumed, clean):
        assert r.dtype == c.dtype
        assert r.tobytes() == c.tobytes()


# --------------------------------------------------------------------- #
# live snapshots + observability


def test_live_snapshots_one_window_staleness():
    queries = [cc_query(N_V), degrees_query(N_V)]
    with obs_bus.scope() as bus:
        res = run_multiquery(queries, _stream(), merge_every=2, **_kw())
        assert isinstance(res, MultiQueryStream)
        assert res.snapshot() is None and res.snapshot_window() == 0
        seen = []
        for i, out in enumerate(iter(res)):
            assert res.snapshot_window() == i + 1
            snap = res.snapshot("cc")
            np.testing.assert_array_equal(snap, np.asarray(out["cc"]))
            both = res.snapshot()
            assert sorted(both) == ["cc", "degrees"]
            seen.append(out)
        assert len(seen) >= 2
        with pytest.raises(ValueError, match="unknown query"):
            res.snapshot("nope")
        counters = bus.snapshot()["counters"]
        assert counters["multiquery.runs"] == 1
        assert counters["multiquery.emissions"] == 2 * len(seen)
        assert counters["multiquery.snapshot_reads"] >= 2 * len(seen)
        assert bus.gauges["multiquery.fused_queries"] == 2


def test_fold_spans_carry_per_query_attribution(tmp_path):
    from gelly_tpu import obs

    queries = [cc_query(N_V), degrees_query(N_V)]
    tracer = obs.SpanTracer()
    with obs.scope() as bus, obs.install(tracer):
        windows = len(list(run_aggregation(
            None, _stream(), queries=queries, merge_every=2, **_kw()
        )))
    folds = tracer.spans("fold")
    assert folds and all(
        s["args"]["queries"] == "cc,degrees" for s in folds
    )
    # one per-query track span per window close
    mq = tracer.spans("multiquery")
    per_query = {}
    for s in mq:
        per_query.setdefault(s["args"]["query"], []).append(s)
    assert sorted(per_query) == ["cc", "degrees"]
    assert all(len(v) == windows for v in per_query.values())
    path = str(tmp_path / "trace.json")
    trace = obs.write_chrome_trace(path, tracer, bus=bus)
    from gelly_tpu.obs.export import validate_chrome_trace

    validate_chrome_trace(trace)


# --------------------------------------------------------------------- #
# multi-tenant integration: N tenants x Q queries, one dispatch


def test_multiquery_plan_as_tenant_tier():
    """A MultiQueryPlan is a valid tenant-tier plan: N tenants x Q
    queries advance in chunks-per-tenant dispatches, and every
    tenant's per-query snapshot is bit-identical to its single-tenant
    fused run."""
    from gelly_tpu.engine.tenants import MultiTenantEngine

    def tenant_edges(seed):
        rng = np.random.default_rng(seed)
        return [(int(a), int(b))
                for a, b in rng.integers(0, N_V, (3 * CHUNK, 2))]

    fused = fuse([cc_query(N_V), degrees_query(N_V)])
    eng = MultiTenantEngine(merge_every=2)
    eng.add_tier("mq", fused, CHUNK)
    n = 4
    for i in range(n):
        eng.admit(i, "mq", chunks=_stream(tenant_edges(i)))
    out = eng.drain()
    assert eng.stats["dispatches"] == 3  # chunks per tenant, not n x 3
    for i in range(n):
        oracle = run_aggregation(
            None, _stream(tenant_edges(i)),
            queries=[cc_query(N_V), degrees_query(N_V)],
            merge_every=2, **_kw()
        ).result()
        assert sorted(out[i]) == ["cc", "degrees"]
        for name in ("cc", "degrees"):
            _assert_tree_identical(oracle[name], out[i][name],
                                   f"tenant{i}/{name}")
