"""Subprocess body for the coordinated multi-host recovery tests
(tests/test_coordinated_recovery.py).

One host of a 2-process gloo mesh (jax.distributed over loopback): joins
the cluster, folds ITS OWN partition of a deterministic edge stream
through a coordinated ``ResilientRunner`` (checkpoint barriers + 2PC
into the shared store, cadenced path flatten), then merges the label
forests across hosts over the mesh and writes its outputs. The parent
SIGKILLs one host mid-stream on the first run; the restarted pair must
re-join at the barrier-agreed position and finish bit-identical to an
uninterrupted run.

Modes (env ``GELLY_COORD_MODE``):

- ``run`` (default) — the coordinated fold described above.
- ``golden`` — NO distributed init, NO coordinator: compute every
  host's expected final local state sequentially (same folds, same
  flatten cadence the runner uses) plus the merged forest, and write
  the same output files. Shares all stream/fold code with ``run``, so
  the bit-identical comparison is apples to apples.

env: COORD, NPROCS, PID_IDX, REPO_ROOT, GELLY_COORD_{STORE,OUT,MODE}
     GELLY_COORD_{EDGES,NV,CHUNK,SLEEP,CADENCE}
     GELLY_COORD_TRACE — when set, each host installs a SpanTracer for
     the coordinated fold and exports its ring to ``<prefix>.<pid>.json``
     (the per-host inputs ``obs.export.stitch_traces`` merges).
Prints ``COORD_RESUMED <position> <chunks_folded>`` after recovery and
``COORD_OK <pid>`` on success.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

N_EDGES = int(os.environ.get("GELLY_COORD_EDGES", "768"))
N_V = int(os.environ.get("GELLY_COORD_NV", "96"))
CHUNK = int(os.environ.get("GELLY_COORD_CHUNK", "16"))
SLEEP_S = float(os.environ.get("GELLY_COORD_SLEEP", "0"))
CADENCE = int(os.environ.get("GELLY_COORD_CADENCE", "4"))
NPROCS = int(os.environ.get("NPROCS", "2"))


def all_edges():
    rng = np.random.default_rng(11)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    return [(int(a), int(b)) for a, b in pairs]


def host_stream(pid):
    """Host ``pid``'s partition: a strided slice, equal chunk counts."""
    from gelly_tpu import edge_stream_from_edges

    part = all_edges()[pid::NPROCS]
    return edge_stream_from_edges(
        part, vertex_capacity=N_V, chunk_size=CHUNK
    )


def build_plan():
    from gelly_tpu.library.connected_components import (
        connected_components,
    )

    agg = connected_components(N_V)
    return (agg, jax.jit(agg.fold), jax.jit(agg.flatten),
            jax.jit(agg.combine))


def write_out(out_path, local, merged_parent, merged_seen, position):
    from gelly_tpu.engine.checkpoint import save_checkpoint

    save_checkpoint(out_path, {
        "parent": np.asarray(local.parent),
        "seen": np.asarray(local.seen),
        "merged_parent": np.asarray(merged_parent),
        "merged_seen": np.asarray(merged_seen),
    }, position=position)


def golden():
    """Every host's expected final local state + the merged forest,
    replicating the coordinated runner's flatten cadence: flatten fires
    at every barrier position (multiples of CADENCE, plus the final
    position when it is past the last cadence point)."""
    agg, fold, flatten, _ = build_plan()
    locals_ = []
    for pid in range(NPROCS):
        s = agg.init()
        pos = 0
        last_ckpt = 0
        for chunk in host_stream(pid):
            s = fold(s, chunk)
            pos += 1
            if pos - last_ckpt >= CADENCE:
                s = flatten(s)
                last_ckpt = pos
        if pos > last_ckpt:
            s = flatten(s)
        locals_.append(jax.device_get(s))
    from gelly_tpu.ops import unionfind

    mp = locals_[0].parent
    ms = locals_[0].seen
    merge = jax.jit(unionfind.merge_forests)
    for other in locals_[1:]:
        mp = merge(mp, other.parent)
        ms = ms | other.seen
    for pid in range(NPROCS):
        write_out(
            os.environ["GELLY_COORD_OUT"] + f".golden{pid}",
            locals_[pid], mp, ms, position=0,
        )
    print("COORD_GOLDEN_OK")


def run():
    pid = int(os.environ["PID_IDX"])
    from gelly_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_multihost(
        coordinator_address=os.environ["COORD"],
        num_processes=NPROCS,
        process_id=pid,
    )
    assert jax.process_count() == NPROCS

    import contextlib

    from gelly_tpu import obs

    trace_prefix = os.environ.get("GELLY_COORD_TRACE")
    tracer = None
    stack = contextlib.ExitStack()
    if trace_prefix:
        # One ring per host: every span/instant this process records —
        # including the mirrored ``coordination.barrier_agreed``
        # instants ``stitch_traces`` aligns clocks on — lands in this
        # host's own exported file.
        tracer = obs.SpanTracer(capacity=16384, heartbeat_every_s=None)
        stack.enter_context(obs.install(tracer))

    from gelly_tpu.engine.coordination import (
        CoordinationConfig,
        Coordinator,
        HostIdentity,
    )
    from gelly_tpu.engine.resilience import (
        ResilienceConfig,
        ResilientRunner,
    )

    agg, fold, flatten, combine = build_plan()

    def step(s, c):
        if SLEEP_S:
            time.sleep(SLEEP_S)
        return fold(s, c), None

    coordinator = Coordinator(
        os.environ["GELLY_COORD_STORE"],
        HostIdentity(pid, NPROCS,
                     coordinator_address=os.environ["COORD"]),
        CoordinationConfig(
            # ttl must exceed the longest beat-free host-side stall
            # (first-dispatch jit compiles ~1-2s on this tier); 3s keeps
            # peer-death detection fast without false positives.
            lease_ttl=3.0, poll_s=0.01, barrier_timeout=30.0,
        ),
    )
    runner = ResilientRunner(
        step,
        host_stream(pid),
        agg.init,
        coordinator=coordinator,
        config=ResilienceConfig(
            checkpoint_every_chunks=CADENCE, watchdog_timeout=60.0,
        ),
        flatten_state=flatten,
        adopt_state=combine,
    )
    try:
        final = runner.run()
    except BaseException as e:  # noqa: BLE001
        # Die HARD: the normal interpreter exit would hang in
        # jax.distributed's atexit shutdown barrier waiting for the
        # already-dead peer — exactly the teardown this harness is
        # crashing on purpose. The parent only asserts rc != 0.
        import traceback

        print("COORD_DEAD", type(e).__name__, e, flush=True)
        traceback.print_exc()
        sys.stderr.flush()
        os._exit(1)
    # start-of-run position = final position minus chunks folded THIS
    # incarnation: the parent asserts the restarted pair re-entered at
    # the manifest's barrier-agreed position.
    print("COORD_RESUMED", runner.position - runner.stats["chunks"],
          runner.stats["chunks"], flush=True)

    # Cross-host merge over the gloo mesh (the timeWindowAll fan-in):
    # every host contributes its local forest; shard 0's view is the
    # global summary.
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.ops import unionfind
    from gelly_tpu.parallel import collectives

    local = jax.device_get(final)
    m = mesh_lib.make_mesh()
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))
    g_parent = jax.make_array_from_callback(
        (NPROCS, N_V), sh,
        lambda idx: jnp.asarray(np.asarray(local.parent)[None, :]),
    )
    g_seen = jax.make_array_from_callback(
        (NPROCS, N_V), sh,
        lambda idx: jnp.asarray(np.asarray(local.seen)[None, :]),
    )

    def merge(parent_blk, seen_blk):
        def comb(a, b):
            return (unionfind.merge_forests(a[0][0], b[0][0])[None],
                    a[1] | b[1])

        return collectives.butterfly_merge(
            comb, (parent_blk, seen_blk), NPROCS
        )

    spec = P(mesh_lib.SHARD_AXIS)
    out_parent, out_seen = mesh_lib.shard_map_fn(
        m, merge, in_specs=(spec, spec), out_specs=(spec, spec),
    )(g_parent, g_seen)
    mp = np.asarray(
        jax.device_get(out_parent.addressable_shards[0].data)
    )[0]
    ms = np.asarray(
        jax.device_get(out_seen.addressable_shards[0].data)
    )[0]
    write_out(
        os.environ["GELLY_COORD_OUT"] + f".{pid}", local, mp, ms,
        position=runner.position,
    )
    if tracer is not None:
        obs.write_chrome_trace(f"{trace_prefix}.{pid}.json", tracer)
    stack.close()
    print("COORD_OK", pid, flush=True)


def main():
    if os.environ.get("GELLY_COORD_MODE", "run") == "golden":
        golden()
    else:
        run()


if __name__ == "__main__":
    main()
