"""Wire trace propagation + push alert subscriptions (ISSUE 20).

Trace side: clients stamp DATA/STACKED frames with (trace_id, span id)
riding the payload dict; the server links wire_recv/staging spans to
the stamp and the engine links fold/checkpoint through the tracer's
position→context registry — one trace shows the whole causal chain.
Retransmitted frames resend the ORIGINAL stamped bytes (same trace),
and all K payloads of a STACKED frame share one frame-level span.

Alert side: SUBSCRIBE registers a filter; EventBus events matching it
are pushed as ALERT frames. Delivery is best-effort and entirely
outside the exactly-once data seq space — asserted here by completing
a data stream bit-exactly while alerts interleave on the connection.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gelly_tpu import obs
from gelly_tpu.engine.aggregation import run_aggregation
from gelly_tpu.ingest import (
    IngestClient,
    IngestServer,
    edge_payload,
)
from gelly_tpu.ingest import wire
from gelly_tpu.ingest.client import IngestError
from gelly_tpu.library.connected_components import connected_components
from gelly_tpu.obs import bus as obs_bus
from gelly_tpu.obs import slo

pytestmark = pytest.mark.ingest


def _drain(server, out):
    def run():
        for seq, payload in server.payloads():
            out.append((seq, payload))
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _one(spans, **match):
    """The single span whose args carry every ``match`` item."""
    hits = [s for s in spans
            if all(s["args"].get(k) == v for k, v in match.items())]
    assert len(hits) == 1, (match, [s["args"] for s in spans])
    return hits[0]


# --------------------------------------------------------------------- #
# trace context on the wire


def test_data_frame_carries_trace_context():
    tracer = obs.SpanTracer(capacity=4096, heartbeat_every_s=None)
    with obs_bus.scope(), obs.install(tracer):
        with IngestServer(queue_depth=8) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send(edge_payload([1], [2]))
                cli.flush(timeout=10)
        t.join(timeout=5)
    assert len(got) == 1
    # The stamp never reaches the consumer.
    assert wire.TRACE_KEY not in got[0][1]
    sends = tracer.spans("client_send")
    recvs = tracer.spans("wire_recv")
    stages = tracer.spans("staging")
    assert len(sends) == len(recvs) == len(stages) == 1
    send, recv, stage = sends[0], recvs[0], stages[0]
    # One trace id end to end, span ids chained send → recv → staging.
    assert send["args"]["trace"] == tracer.trace_id
    assert recv["args"]["trace"] == tracer.trace_id
    assert stage["args"]["trace"] == tracer.trace_id
    assert recv["args"]["parent"] == send["args"]["span"]
    assert stage["args"]["parent"] == recv["args"]["span"]
    # The staged position is bound for the engine's fold to pick up.
    assert tracer.ctx(0) == (tracer.trace_id, stage["args"]["span"])


def test_stacked_frame_links_all_payloads_to_one_frame_span():
    K = 4
    tracer = obs.SpanTracer(capacity=4096, heartbeat_every_s=None)
    with obs_bus.scope(), obs.install(tracer):
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port, stack=K) as cli:
                for i in range(K):
                    cli.send(edge_payload([i], [i + 1]))
                cli.flush(timeout=10)
        t.join(timeout=5)
    assert [s for s, _ in got] == list(range(K))
    # ONE frame-level client span covering the whole stack...
    send = _one(tracer.spans("client_send"), stack=K)
    recv = _one(tracer.spans("wire_recv"), stack=K)
    assert recv["args"]["parent"] == send["args"]["span"]
    # ...and every payload position staged under it, bound to the one
    # staging span of the one wire frame.
    stage = _one(tracer.spans("staging"), stack=K)
    assert stage["args"]["parent"] == recv["args"]["span"]
    for pos in range(K):
        assert tracer.ctx(pos) == (tracer.trace_id,
                                   stage["args"]["span"])


def test_retransmit_reuses_original_trace_context():
    """REJECT-driven retransmits resend the ORIGINAL stamped frame
    bytes: no second client_send span, no second trace context — the
    staging context after the retransmit is the first send's."""
    tracer = obs.SpanTracer(capacity=4096, heartbeat_every_s=None)
    with obs_bus.scope() as bus, obs.install(tracer):
        with IngestServer(queue_depth=8) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            # Corrupt the first DATA frame on the wire (one payload
            # byte flipped AFTER packing — the stamped bytes in the
            # resend buffer stay intact): the server rejects, the
            # client retransmits the buffered original.
            orig = cli._raw_send
            left = [1]

            def corrupting(frame):
                if left[0] and len(frame) > 100:  # only DATA is this big
                    left[0] -= 1
                    frame = frame[:-1] + bytes([frame[-1] ^ 0xFF])
                orig(frame)

            cli._raw_send = corrupting
            cli.send(edge_payload([1], [2]))
            cli.flush(timeout=10)
            cli.close()
            assert left[0] == 0  # the corruption really happened
        t.join(timeout=5)
        resent = bus.snapshot()["counters"].get("ingest.frames_resent", 0)
    assert resent >= 1
    assert len(got) == 1
    sends = tracer.spans("client_send")
    assert len(sends) == 1  # retransmit minted NO new span
    # Every server-side receive of that seq carries the one original
    # context (duplicate receives are possible; fresh traces are not).
    recvs = tracer.spans("wire_recv")
    assert recvs, "no wire_recv spans recorded"
    for r in recvs:
        assert r["args"]["trace"] == tracer.trace_id
        assert r["args"]["parent"] == sends[0]["args"]["span"]


def test_unstamped_and_malformed_stamps_degrade_silently():
    # pop_trace: absent and malformed stamps are both "no context".
    assert wire.pop_trace({"x": np.arange(2)}) is None
    bad = {wire.TRACE_KEY: np.arange(3, dtype=np.uint64)}
    assert wire.pop_trace(bad) is None
    assert wire.TRACE_KEY not in bad  # still consumed off the payload
    # A malformed stamp on the wire is not a protocol error: the frame
    # stages fine, minus the stamp (no tracer installed → the client
    # passes the caller's dict through, bogus "_trace" key included).
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=8) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                p = edge_payload([1], [2])
                p[wire.TRACE_KEY] = np.arange(5, dtype=np.uint64)
                cli.send(p)
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert bus.snapshot()["counters"].get(
            "ingest.frames_rejected", 0) == 0
    assert len(got) == 1
    assert wire.TRACE_KEY not in got[0][1]
    assert got[0][1]["src"].tolist() == [1]


def test_e2e_wire_to_checkpoint_shares_one_trace(tmp_path):
    """The acceptance chain: client send → wire recv → staging → fold →
    checkpoint, one trace_id, span ids linked stage to stage — and the
    exported trace validates."""
    N_V = 64
    agg = connected_components(N_V)
    rng = np.random.default_rng(7)
    edges = rng.integers(0, N_V, (48, 2))
    tracer = obs.SpanTracer(capacity=1 << 14, heartbeat_every_s=None)
    with obs_bus.scope() as bus, obs.install(tracer):
        with IngestServer(queue_depth=16, stop_on_bye=True) as srv:
            def feed():
                with IngestClient("127.0.0.1", srv.port) as cli:
                    for i in range(0, 48, 16):
                        cli.send(edge_payload(edges[i:i + 16, 0],
                                              edges[i:i + 16, 1]))
                    cli.flush(timeout=30)
            ft = threading.Thread(target=feed, daemon=True)
            ft.start()
            run_aggregation(
                agg, srv.chunks(16, N_V), merge_every=1,
                checkpoint_path=str(tmp_path / "ck.npz"),
                checkpoint_every=1, ingest_workers=0, prefetch_depth=0,
                h2d_depth=0,
            ).result()
            ft.join(timeout=30)
        trace = obs.write_chrome_trace(
            str(tmp_path / "trace_e2e_wire.json"), tracer, bus=bus)
    assert trace["otherData"]["trace_id"] == tracer.trace_id
    # Follow chunk 0's causal chain by explicit span-id links.
    send = _one(tracer.spans("client_send"), seq=0)
    recv = _one(tracer.spans("wire_recv"), seq=0)
    stage = _one(tracer.spans("staging"), seq=0)
    assert recv["args"]["parent"] == send["args"]["span"]
    assert stage["args"]["parent"] == recv["args"]["span"]
    # The fold of the first unit links to chunk 0's staging span...
    folds = [s for s in tracer.spans("fold")
             if s["args"].get("parent") == stage["args"]["span"]]
    assert len(folds) == 1
    fold = folds[0]
    assert fold["args"]["trace"] == tracer.trace_id
    # ...and a checkpoint links to a fold span, closing the chain.
    fold_ids = {s["args"]["span"] for s in tracer.spans("fold")}
    ckpts = tracer.spans("checkpoint")
    assert ckpts
    linked = [c for c in ckpts if c["args"].get("parent") in fold_ids]
    assert linked, [c["args"] for c in ckpts]
    for c in linked:
        assert c["args"]["trace"] == tracer.trace_id


# --------------------------------------------------------------------- #
# push alert subscriptions


def test_subscribe_pushes_matching_alerts_only():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=8) as srv:
            with IngestClient("127.0.0.1", srv.port) as cli:
                seen = []
                sub_id = cli.subscribe(events=("slo.",),
                                       on_alert=seen.append)
                assert sub_id >= 1
                assert bus.gauges.get("alerts.subscribers") == 1
                bus.emit("slo.breach", slo="fold_p99_ms", tenant=None,
                         value=50.0, threshold=10.0, burn_rate=1.0)
                bus.emit("alerts.degree_spike", degree=99.0)  # filtered
                deadline = time.monotonic() + 5
                while not seen and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert len(seen) == 1
                alert = seen[0]
                assert alert["event"] == "slo.breach"
                assert alert["sub_id"] == sub_id
                assert alert["fields"]["slo"] == "fold_p99_ms"
                assert alert["fields"]["value"] == 50.0
                assert cli.alerts[-1] == alert
        counters = bus.snapshot()["counters"]
        assert counters["alerts.subscriptions"] == 1
        assert counters["alerts.pushed"] == 1
        assert counters["ingest.alerts_received"] == 1
        # Teardown returned the subscriber gauge to zero.
        assert bus.gauges.get("alerts.subscribers") == 0


def test_subscribe_tenant_and_slo_filters():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=8) as srv:
            with IngestClient("127.0.0.1", srv.port) as cli:
                seen = []
                cli.subscribe(events=("slo.",), tenant=3,
                              slo="backlog_age_s", on_alert=seen.append)
                bus.emit("slo.breach", slo="backlog_age_s", tenant=7,
                         value=9.0)   # wrong tenant
                bus.emit("slo.breach", slo="fold_p99_ms", tenant=3,
                         value=9.0)   # wrong slo
                bus.emit("slo.breach", slo="backlog_age_s", tenant=3,
                         value=9.0)   # match
                deadline = time.monotonic() + 5
                while not seen and time.monotonic() < deadline:
                    time.sleep(0.01)
                time.sleep(0.1)  # would-be stragglers
                assert [a["fields"]["tenant"] for a in seen] == [3]
                with pytest.raises(IngestError, match="malformed"):
                    cli._sub_evt.clear()
                    cli._sock.sendall(wire.pack_frame(
                        wire.SUBSCRIBE, 99, b"\xff\xfe"))
                    if not cli._sub_evt.wait(5):
                        raise AssertionError("no SUBSCRIBE reply")
                    payload = wire.unpack_json(cli._sub_payload)
                    if not payload.get("ok"):
                        raise IngestError(payload.get("error", "?"))


def test_degree_spike_stream_delivers_push_alert():
    """The acceptance scenario: a seeded degree-spike stream — uniform
    chunks, then one hub chunk — drives the summary-delta watch on the
    server side, and the subscribed loopback client receives the
    ``alerts.degree_spike`` ALERT frame."""
    rng = np.random.default_rng(11)
    N_V = 256
    with obs_bus.scope() as bus:
        watch = slo.SummaryDeltaWatch(bus=bus, spike_factor=4.0,
                                      min_degree=8)
        with IngestServer(queue_depth=32) as srv:
            def consume():
                for _seq, payload in srv.payloads():
                    deg = np.bincount(payload["dst"], minlength=N_V)
                    watch.observe(max_degree=int(deg.max()))
            ct = threading.Thread(target=consume, daemon=True)
            ct.start()
            with IngestClient("127.0.0.1", srv.port) as cli:
                spikes = []
                cli.subscribe(events=("alerts.degree_spike",),
                              on_alert=spikes.append)
                # Steady uniform chunks build the EMA baseline...
                for _ in range(6):
                    e = rng.integers(0, N_V, (64, 2))
                    cli.send(edge_payload(e[:, 0], e[:, 1]))
                cli.flush(timeout=10)
                # ...then the hub chunk: every edge into vertex 0.
                src = rng.integers(0, N_V, 64)
                cli.send(edge_payload(src, np.zeros(64, dtype=np.int64)))
                cli.flush(timeout=10)
                deadline = time.monotonic() + 5
                while not spikes and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert spikes, "degree-spike ALERT never arrived"
                assert spikes[0]["event"] == "alerts.degree_spike"
                assert spikes[0]["fields"]["degree"] >= 32.0
        assert bus.snapshot()["counters"]["alerts.pushed"] >= 1


def test_blown_backlog_slo_pushes_breach_alert_end_to_end():
    """SLO plane + alert plane, end to end: a deliberately-blown
    ``backlog_age_max_s`` SLO (ingress stamped, never retired) raises
    its burn-rate gauge AND the breach lands at the subscribed client
    as a pushed ALERT frame."""
    with obs_bus.scope() as bus:
        plane = slo.SloPlane([slo.backlog_age_max_s(0.005)], bus=bus)
        with IngestServer(queue_depth=8) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                breaches = []
                cli.subscribe(events=("slo.breach",),
                              on_alert=breaches.append)
                cli.send(edge_payload([1], [2]))
                cli.flush(timeout=10)
                bus.watermarks.stamp("stream", 0)
                time.sleep(0.02)  # age past the 5 ms budget
                assert plane.tick() == 1
                assert bus.gauges[
                    "slo.backlog_age_max_s.burn_rate"] == 1.0
                deadline = time.monotonic() + 5
                while not breaches and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert breaches, "breach ALERT never arrived"
                fields = breaches[0]["fields"]
                assert fields["slo"] == "backlog_age_max_s"
                assert fields["value"] >= 0.005
        t.join(timeout=5)


def test_alert_plane_stays_outside_data_seq_space():
    """Alerts interleaving with DATA on one connection must not
    perturb the exactly-once stream: every chunk lands once, acks
    complete, the resend buffer drains — while ALERT frames flow."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=32) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                seen = []
                cli.subscribe(events=("alerts.",), on_alert=seen.append)
                for i in range(20):
                    cli.send(edge_payload([i], [i + 1]))
                    if i % 5 == 0:
                        bus.emit("alerts.degree_spike", degree=float(i))
                cli.flush(timeout=10)
                deadline = time.monotonic() + 5
                while len(seen) < 4 and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert cli.acked == 20
                assert cli.unacked_count == 0
                assert len(seen) == 4
        t.join(timeout=5)
        assert [s for s, _ in got] == list(range(20))
        counters = bus.snapshot()["counters"]
        assert counters["ingest.chunks_enqueued"] == 20
        assert counters["alerts.pushed"] == 4
        assert counters.get("alerts.dropped", 0) == 0


def test_router_attached_heartbeat_carries_tenant_fields():
    """Satellite 6, router-attached: a TenantRouter feeding the tenant
    scheduler from a live wire server beats with the full tenant field
    set — tenants_active, tenants_queue_depth, backlog_age_max_s and
    slo_breaching — mirrored onto the installed tracer."""
    from gelly_tpu.engine.tenants import MultiTenantEngine
    from gelly_tpu.ingest import TenantRouter
    from gelly_tpu.library.connected_components import cc_tenant_tier

    n_v = 64
    tracer = obs.SpanTracer(capacity=4096, heartbeat_every_s=0.0)
    with obs_bus.scope(), obs.install(tracer):
        agg, cap = cc_tenant_tier(n_v, chunk_capacity=16)
        eng = MultiTenantEngine(merge_every=1).start()
        router = TenantRouter(eng, "small", vertex_capacity=n_v)
        eng.add_tier("small", agg, cap)
        with IngestServer(queue_depth=16) as srv:
            router.attach(srv)
            with IngestClient("127.0.0.1", srv.port) as cli:
                rng = np.random.default_rng(7)
                for tid in (3, 4):
                    for _ in range(2):
                        p = edge_payload(rng.integers(0, n_v, 8),
                                         rng.integers(0, n_v, 8))
                        p["tenant"] = np.array([tid], np.int64)
                        cli.send(p)
                cli.flush(timeout=30)
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    try:
                        if (eng.position(3) >= 2
                                and eng.position(4) >= 2):
                            break
                    except KeyError:
                        pass
                    time.sleep(0.01)
        eng.stop()
    beats = tracer.instants("heartbeat")
    assert beats, "router-attached scheduler never beat"
    line = beats[-1]["args"]
    for field in ("tenants_active", "tenants_queue_depth",
                  "backlog_age_max_s", "slo_breaching"):
        assert field in line, (field, line)
    assert line["slo_breaching"] == 0
