"""gelly_tpu.ingest.readers: sharded byte-range sources.

Covers range alignment (text split rule + binary record multiples),
the deterministic round-robin merge schedule and its resume math,
per-shard seekable resume (recorded byte offsets; canonical-schedule
continuation mid-cycle), the engine's source-provider path (labels
bit-identical to the single-iterator executor, no global produce span,
one compress track per reader lane), composition with the resilient
driver's last-retired-chunk rule, the ingest fault boundary, the
shard→host routing table with the coordination re-shard hook, and the
``EdgeChunkSource.iter_from`` O(1)-resume regression.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gelly_tpu.engine import faults
from gelly_tpu.ingest import (
    ShardRoutingTable,
    ShardedEdgeSource,
    byte_ranges,
    edge_stream_from_sharded_file,
    write_binary_edges,
)
from gelly_tpu.ingest.readers import (
    _unit_starts,
    consumed_after,
    rr_order,
)
from gelly_tpu.obs import bus as obs_bus

pytestmark = pytest.mark.ingest

NV = 128


def _edges(n=900, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(0, NV, n), rng.integers(0, NV, n)


@pytest.fixture
def text_file(tmp_path):
    src, dst = _edges()
    p = tmp_path / "edges.txt"
    with open(p, "w") as f:
        f.write("% header comment\n")
        for i, (a, b) in enumerate(zip(src, dst)):
            f.write(f"{a} {b}\n")
            if i % 97 == 0:
                f.write("# interleaved comment\n")
            if i % 131 == 0:
                f.write("not-an-edge\n")
    return str(p), src, dst


@pytest.fixture
def bin_file(tmp_path):
    src, dst = _edges()
    p = tmp_path / "edges.bin"
    write_binary_edges(str(p), src, dst)
    return str(p), src, dst


def _pairs(chunks):
    out = []
    for c in chunks:
        m = np.asarray(c.valid).astype(bool)
        out.extend(zip(np.asarray(c.raw_src)[m].tolist(),
                       np.asarray(c.raw_dst)[m].tolist()))
    return out


# --------------------------------------------------------------------- #
# ranges + schedule math


def test_byte_ranges_cover_file_and_align_bin(tmp_path, bin_file):
    path, src, _ = bin_file
    size = os.path.getsize(path)
    for s in (1, 2, 3, 5):
        r = byte_ranges(path, s)
        assert r[0][0] == 0 and r[-1][1] == size
        assert all(a[1] == b[0] for a, b in zip(r, r[1:]))
        assert all(lo % 16 == 0 and hi % 16 == 0 for lo, hi in r)


def test_byte_ranges_rejects_misaligned_bin(tmp_path):
    p = tmp_path / "bad.bin"
    p.write_bytes(b"x" * 17)
    with pytest.raises(ValueError, match="multiple"):
        byte_ranges(str(p), 2)


def test_rr_order_and_consumed_after():
    counts = [3, 1, 2]
    order = list(rr_order(counts))
    assert order == [0, 1, 2, 0, 2, 0]
    for k in range(sum(counts) + 1):
        per = consumed_after(counts, k)
        assert sum(per) == k
        assert per == [order[:k].count(s) for s in range(3)]
    with pytest.raises(ValueError, match="exceeds"):
        consumed_after(counts, 7)


def test_unit_starts_alignment():
    counts = [5, 3]
    # units of 2: shard0 -> [2,2,1], shard1 -> [2,1]; schedule
    # interleaves 0,1,0,1,0 with per-unit chunk counts 2,2,2,1,1.
    starts, skipped = _unit_starts(counts, 2, 4)
    assert (starts, skipped) == ([1, 1], 2)
    # 7 chunks = units (s0,2) (s1,2) (s0,2) (s1,1) along the schedule.
    starts, skipped = _unit_starts(counts, 2, 7)
    assert (starts, skipped) == ([2, 2], 4)
    with pytest.raises(ValueError, match="unit boundary"):
        _unit_starts(counts, 2, 3)


# --------------------------------------------------------------------- #
# reading + resume


@pytest.mark.parametrize("kind", ["text", "bin"])
@pytest.mark.parametrize("shards", [1, 2, 3, 4])
def test_sharded_read_is_exact_and_deterministic(kind, shards, text_file,
                                                 bin_file):
    path, src, dst = text_file if kind == "text" else bin_file
    s0 = ShardedEdgeSource(path, shards=shards, chunk_size=64,
                           vertex_capacity=NV)
    chunks = list(s0)
    # Every record exactly once (multiset equality).
    assert sorted(_pairs(chunks)) == sorted(zip(src.tolist(), dst.tolist()))
    # Deterministic merge order: a second pass is identical.
    again = list(ShardedEdgeSource(path, shards=shards, chunk_size=64,
                                   vertex_capacity=NV))
    assert _pairs(chunks) == _pairs(again)


@pytest.mark.parametrize("kind", ["text", "bin"])
def test_resume_continues_canonical_schedule(kind, text_file, bin_file):
    path, _, _ = text_file if kind == "text" else bin_file
    full = [_pairs([c]) for c in ShardedEdgeSource(
        path, shards=3, chunk_size=64, vertex_capacity=NV)]
    n = len(full)
    for pos in (0, 1, 2, n // 2, n - 1, n):
        # Fresh object (no recorded offsets) AND warm object (offsets
        # recorded by the full pass) must both produce exactly the
        # canonical suffix — including mid-cycle continuations.
        fresh = ShardedEdgeSource(path, shards=3, chunk_size=64,
                                  vertex_capacity=NV)
        assert [_pairs([c]) for c in fresh.iter_from(pos)] == full[pos:]
        warm = ShardedEdgeSource(path, shards=3, chunk_size=64,
                                 vertex_capacity=NV)
        list(warm)  # record offsets + counts
        assert [_pairs([c]) for c in warm.iter_from(pos)] == full[pos:]


def test_recorded_offsets_enable_direct_seek(text_file):
    path, _, _ = text_file
    src = ShardedEdgeSource(path, shards=2, chunk_size=64,
                            vertex_capacity=NV)
    list(src)
    counts = src.shard_counts()
    for s in range(2):
        offs = src.recorded_offsets(s)
        assert len(offs) == counts[s]
        assert offs == sorted(offs)
        # Seeking a lane directly at a recorded offset reproduces the
        # same chunk: the offsets really are record starts.
        for idx in (0, counts[s] // 2):
            direct = next(iter(src._read_shard(s, idx)))
            fresh = ShardedEdgeSource(path, shards=2, chunk_size=64,
                                      vertex_capacity=NV)
            scan = None
            for i, c in enumerate(fresh._read_shard(s, 0)):
                if i == idx:
                    scan = c
                    break
            assert _pairs([direct]) == _pairs([scan])


def test_sharded_source_rejects_stateful_table(text_file):
    from gelly_tpu.core.vertices import VertexTable

    path, _, _ = text_file
    with pytest.raises(ValueError, match="first-seen"):
        ShardedEdgeSource(path, shards=2, table=VertexTable())


def test_out_of_range_id_raises(tmp_path):
    p = tmp_path / "e.bin"
    write_binary_edges(str(p), [1, 999], [2, 3])
    src = ShardedEdgeSource(str(p), shards=1, chunk_size=4,
                            vertex_capacity=8)
    with pytest.raises(ValueError, match="out of range"):
        list(src)


def test_ingest_fault_boundary_fires_in_reader(bin_file):
    path, _, _ = bin_file
    src = ShardedEdgeSource(path, shards=2, chunk_size=64,
                            vertex_capacity=NV)
    plan = faults.FaultPlan([faults.Fault(boundary="ingest", at=1)])
    with faults.install(plan):
        with pytest.raises(faults.FaultInjected):
            list(src)
    assert ("ingest", 1, "raise") in plan.fired


# --------------------------------------------------------------------- #
# engine integration (source_provider)


def _cc_labels_reference(src, dst):
    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.library.connected_components import connected_components

    stream = edge_stream_from_edges(
        list(zip(src.tolist(), dst.tolist())), vertex_capacity=NV,
        chunk_size=64,
    )
    return np.asarray(
        stream.aggregate(connected_components(NV), merge_every=4).result()
    )


def test_source_provider_labels_match_and_lanes_are_independent(bin_file):
    from gelly_tpu import obs
    from gelly_tpu.library.connected_components import connected_components

    path, src, dst = bin_file
    want = _cc_labels_reference(src, dst)
    stream = edge_stream_from_sharded_file(path, NV, shards=3,
                                           chunk_size=64)
    tracer = obs.SpanTracer(heartbeat_every_s=None)
    with obs.scope(), obs.install(tracer):
        got = np.asarray(
            stream.aggregate(connected_components(NV), merge_every=4,
                             source_provider=True).result()
        )
    np.testing.assert_array_equal(got, want)
    # The tentpole claim: NO global produce span — each lane compresses
    # on its own thread/track.
    assert tracer.spans("produce") == []
    threads = {s["thread"] for s in tracer.spans("compress")}
    assert {"gelly-reader_0", "gelly-reader_1", "gelly-reader_2"} <= threads


def test_source_provider_checkpoint_resume(bin_file, tmp_path):
    from gelly_tpu.engine.checkpoint import load_checkpoint
    from gelly_tpu.library.connected_components import connected_components

    path, src, dst = bin_file
    want = _cc_labels_reference(src, dst)
    ck = str(tmp_path / "ck.npz")
    stream = edge_stream_from_sharded_file(path, NV, shards=3,
                                           chunk_size=64)
    it = iter(stream.aggregate(connected_components(NV), merge_every=4,
                               source_provider=True, checkpoint_path=ck,
                               checkpoint_every=1))
    for _ in range(3):  # abandon mid-stream with a checkpoint on disk
        next(it)
    it.close()
    _, pos, _ = load_checkpoint(ck)
    assert 0 < pos < ShardedEdgeSource(path, shards=3, chunk_size=64,
                                       vertex_capacity=NV).num_chunks
    # A FRESH process (new source object, no recorded offsets) resumes
    # through the provider: per-shard positions derived from the single
    # recorded last-retired-chunk position.
    stream2 = edge_stream_from_sharded_file(path, NV, shards=3,
                                            chunk_size=64)
    got = np.asarray(
        stream2.aggregate(connected_components(NV), merge_every=4,
                          source_provider=True, checkpoint_path=ck,
                          resume=True).result()
    )
    np.testing.assert_array_equal(got, want)


def test_source_provider_mode_validation(bin_file):
    from gelly_tpu.library.connected_components import connected_components

    path, _, _ = bin_file
    stream = edge_stream_from_sharded_file(path, NV, shards=2,
                                           chunk_size=64)
    with pytest.raises(ValueError, match="merge_every-only"):
        stream.aggregate(connected_components(NV), window_ms=10,
                         source_provider=True).result()
    from gelly_tpu import edge_stream_from_edges

    # A plain array-backed source is not a provider (no stage_units).
    plain = edge_stream_from_edges([(0, 1)], vertex_capacity=4)
    with pytest.raises(ValueError, match="stage_units"):
        plain.aggregate(connected_components(4),
                        source_provider=True).result()
    # A derived stream has no source at all.
    derived = plain.reverse()
    with pytest.raises(ValueError, match="source_provider=True"):
        derived.aggregate(connected_components(4),
                          source_provider=True).result()
    # Worker knobs size the prefetch_map pool the provider replaces:
    # passing both is a silent no-op trap, so it refuses loudly.
    with pytest.raises(ValueError, match="shard count IS the lane"):
        stream.aggregate(connected_components(NV), merge_every=4,
                         source_provider=True, codec_workers=8).result()


def test_source_provider_rejects_ordered_stacker(bin_file):
    from gelly_tpu.library.connected_components import (
        connected_components_compact,
    )

    path, _, _ = bin_file
    stream = edge_stream_from_sharded_file(path, NV, shards=2,
                                           chunk_size=64)
    agg = connected_components_compact(NV)
    assert agg.stack_ordered  # the plan this guard exists for
    with pytest.raises(ValueError, match="ordered stacker"):
        stream.aggregate(agg, merge_every=4, source_provider=True).result()


def test_resilient_runner_composes_with_sharded_source(bin_file, tmp_path):
    import jax

    from gelly_tpu.library.connected_components import connected_components

    path, src, dst = bin_file
    want = _cc_labels_reference(src, dst)
    agg = connected_components(NV)
    fold = jax.jit(agg.fold)

    from gelly_tpu.engine.resilience import (
        ResilienceConfig,
        ResilientRunner,
    )

    source = ShardedEdgeSource(path, shards=4, chunk_size=64,
                               vertex_capacity=NV)
    runner = ResilientRunner(
        lambda s, c: (fold(s, c), None), source, agg.init,
        checkpoint_dir=str(tmp_path / "ckd"),
        config=ResilienceConfig(checkpoint_every_chunks=5,
                                watchdog_timeout=None),
    )
    final = runner.run()
    got = np.asarray(jax.jit(agg.transform)(final))
    np.testing.assert_array_equal(got, want)
    assert runner.position == source.num_chunks

    # Resume from the rotation mid-stream: a second runner over a FRESH
    # source object continues from the newest checkpoint through
    # iter_from (per-shard seeks) and lands bit-identical.
    source2 = ShardedEdgeSource(path, shards=4, chunk_size=64,
                                vertex_capacity=NV)
    runner2 = ResilientRunner(
        lambda s, c: (fold(s, c), None), source2, agg.init,
        checkpoint_dir=str(tmp_path / "ckd"),
        config=ResilienceConfig(checkpoint_every_chunks=5,
                                watchdog_timeout=None),
    )
    resumed = runner2.run()
    assert runner2.stats["resumed_from"] is not None
    for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(resumed)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# --------------------------------------------------------------------- #
# routing table + coordination re-shard hook


def test_routing_table_reroute_matches_adoption_rule():
    with obs_bus.scope() as bus:
        rt = ShardRoutingTable(num_shards=8, num_hosts=4)
        assert rt.shards_for(3) == [3, 7]
        moved = rt.reroute(4, 2)
        # Orphan hosts 2,3 -> survivors 0,1 (j % new_count), shards
        # following their adopted state.
        assert moved == {2: 0, 3: 1, 6: 0, 7: 1}
        assert rt.shards_for(0) == [0, 2, 4, 6]
        assert rt.shards_for(1) == [1, 3, 5, 7]
        assert bus.snapshot()["counters"]["ingest.reshards"] == 1
    with pytest.raises(ValueError, match="new_count"):
        rt.reroute(2, 3)


def test_coordinator_recover_drives_ingest_reshard(tmp_path):
    """The degraded re-join rung calls the reshard hook with
    (old_count, new_count) — the lost host's reader shards land on the
    SAME survivor that adopted its state shards."""
    from test_coordination import _cfg, _committed_two_host_store

    from gelly_tpu.engine.coordination import Coordinator, HostIdentity

    _committed_two_host_store(tmp_path)
    rt = ShardRoutingTable(num_shards=4, num_hosts=2)
    with obs_bus.scope():
        co = Coordinator(str(tmp_path), HostIdentity(0, 1), _cfg())
        _state, pos, _meta = co.recover(
            like={"x": np.zeros(4, dtype=np.int64)},
            adopt=lambda a, b: {"x": a["x"] + b["x"]},
            reshard=rt.reroute,
        )
    assert pos == 8
    assert rt.num_hosts == 1
    assert rt.shards_for(0) == [0, 1, 2, 3]


# --------------------------------------------------------------------- #
# EdgeChunkSource.iter_from O(1) resume (satellite regression)


def test_edge_chunk_source_resume_skips_warm_prefix():
    """iter_from used to re-encode the whole prefix through the stateful
    VertexTable on every resume (O(position) per restart); the recorded
    watermark makes an in-process resume O(1) — zero encode calls for
    the already-warm prefix — while staying bit-identical."""
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.vertices import VertexTable

    rng = np.random.default_rng(5)
    src = rng.integers(10_000, 99_999, 640)
    dst = rng.integers(10_000, 99_999, 640)

    class CountingTable(VertexTable):
        def __init__(self):
            super().__init__()
            self.encode_calls = 0

        def encode(self, raw_ids):
            self.encode_calls += 1
            return super().encode(raw_ids)

    table = CountingTable()
    source = EdgeChunkSource(src, dst, chunk_size=64, table=table)
    full = [(np.asarray(c.src).tolist()) for c in source]
    assert len(full) == 10

    # Resume at chunk 7 on the SAME source object: the prefix is warm,
    # so the only encode calls are for the 3 remaining chunks (src+dst
    # each) — none for the 7 skipped ones.
    table.encode_calls = 0
    resumed = [(np.asarray(c.src).tolist()) for c in source.iter_from(7)]
    assert resumed == full[7:]
    assert table.encode_calls == 2 * 3

    # A COLD source (fresh table) still warms the prefix — correctness
    # over speed — and stays bit-identical.
    cold_table = CountingTable()
    cold = EdgeChunkSource(src, dst, chunk_size=64, table=cold_table)
    resumed_cold = [(np.asarray(c.src).tolist())
                    for c in cold.iter_from(7)]
    assert resumed_cold == full[7:]
    assert cold_table.encode_calls == 2 * 10  # 7 warm + 3 yielded

    # Partial first pass: the watermark covers only what was actually
    # encoded; a later resume encodes exactly the gap.
    t2 = CountingTable()
    s2 = EdgeChunkSource(src, dst, chunk_size=64, table=t2)
    it = iter(s2)
    for _ in range(4):
        next(it)
    it.close()
    t2.encode_calls = 0
    resumed2 = [(np.asarray(c.src).tolist()) for c in s2.iter_from(7)]
    assert resumed2 == full[7:]
    assert t2.encode_calls == 2 * 3 + 2 * 3  # warm chunks 4..6 + yield 7..9
