"""Example-CLI smoke tests — the reference's ITCase tier (SURVEY §4 tier 3:
WindowTrianglesITCase / DegreeDistributionITCase invoke the example main()
directly). Each example's ``main([])`` runs its built-in default data; where
the reference pins golden output, we assert it.
"""

import importlib
import io
import os
import sys
from contextlib import redirect_stdout

import pytest

EXAMPLES_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)

# Derived from the directory so a new example cannot ship without a smoke
# test.
ALL_EXAMPLES = sorted(
    f[:-3]
    for f in os.listdir(EXAMPLES_DIR)
    if f.endswith(".py") and f != "_util.py"
)


def run_main(name, args=()):
    if EXAMPLES_DIR not in sys.path:
        sys.path.insert(0, EXAMPLES_DIR)
    mod = importlib.import_module(name)
    buf = io.StringIO()
    with redirect_stdout(buf):
        mod.main(list(args))
    return buf.getvalue()


@pytest.mark.parametrize("name", ALL_EXAMPLES)
def test_example_main_runs_on_default_data(name):
    out = run_main(name)
    assert out.strip(), f"{name} produced no output"


def test_window_triangles_golden():
    # WindowTrianglesITCase golden: "(2,399) (3,799) (2,1199)".
    out = run_main("window_triangles")
    assert "(2,399)" in out and "(3,799)" in out and "(2,1199)" in out


def test_degree_distribution_golden():
    # DegreeDistributionITCase: deletion drives a degree back down; the
    # final distribution lines are (degree, count) pairs.
    out = run_main("degree_distribution")
    assert "(1,2)" in out


def test_connected_components_components():
    # The example's built-in default mirrors the reference's odd/even
    # sequence data (ConnectedComponentsExample.java:121-134): the stream
    # must converge to exactly two components, odds and evens.
    out = run_main("connected_components")
    assert out.startswith("1: [1, 3, 5")
    assert "\n2: [2, 4, 6" in out
    assert out.count("\n") == 2  # two component lines, one trailing \n


def test_matching_total_weight():
    out = run_main("centralized_weighted_matching")
    assert "total weight:" in out


def test_connected_components_fused_queries():
    # --queries fuses CC + degrees + bipartiteness over the one default
    # stream: the same odd/even components as the single-query run, a
    # degree line, and bipartiteness ok (the odd and even chains are
    # paths — no odd cycles).
    out = run_main("connected_components",
                   ["--queries=cc,degrees,bipartiteness"])
    assert "cc 1: [1, 3, 5" in out
    assert "cc 2: [2, 4, 6" in out
    assert "degrees top:" in out
    assert "bipartiteness: ok" in out
    with pytest.raises(SystemExit, match="single-query"):
        run_main("connected_components",
                 ["--queries=cc", "--checkpoint-dir=/tmp/x"])
    with pytest.raises(SystemExit, match="unknown --queries"):
        run_main("connected_components", ["--queries=nope"])


def test_connected_components_stats_flag_validation():
    # --stats shapes the SERVER's telemetry; alone it must refuse
    # loudly, never silently enable process-wide recording.
    with pytest.raises(SystemExit, match="pair it with --serve"):
        run_main("connected_components", ["--stats"])
    from gelly_tpu import obs

    assert not obs.recording()  # the refusal never flipped the switch
