"""Subprocess body for the pipelined-executor kill -9 crash test
(test_pipeline.py).

Runs the FULL pipelined engine path — codec workers, double-buffered H2D,
donated folds, window checkpoints via ``aggregate(checkpoint_path=...)``
— over a deterministic stream, throttled so the kill lands with units in
flight in the compress/H2D buffers. The second incarnation resumes
(``resume=True`` once the checkpoint exists) and must produce final
labels bit-identical to an uninterrupted run, proving the
last-retired-chunk position rule: staged-but-unfolded units (including
their stateful compact-id assignments) are re-read, never lost or
double-folded.

argv: <checkpoint_path> <out_npz> [emit_sleep_seconds]
Env: GELLY_PIPE_EDGES / _NV / _CHUNK override the stream shape.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gelly_tpu import edge_stream_from_edges  # noqa: E402
from gelly_tpu.engine.checkpoint import save_checkpoint  # noqa: E402
from gelly_tpu.library.connected_components import (  # noqa: E402
    connected_components,
)

N_EDGES = int(os.environ.get("GELLY_PIPE_EDGES", "2048"))
N_V = int(os.environ.get("GELLY_PIPE_NV", "128"))
CHUNK = int(os.environ.get("GELLY_PIPE_CHUNK", "32"))


def build_stream():
    rng = np.random.default_rng(13)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    return edge_stream_from_edges(
        [(int(a), int(b)) for a, b in pairs],
        vertex_capacity=N_V, chunk_size=CHUNK,
    )


def main(argv):
    ckpt_path, out_path = argv[0], argv[1]
    sleep_s = float(argv[2]) if len(argv) > 2 else 0.0
    stream = build_stream()
    # The compact plan: stateful host cid session (the hardest resume —
    # on_resume must rebuild it from the restored summary, dropping any
    # in-flight staged assignments the crash stranded).
    agg = connected_components(N_V, merge="gather", codec="compact",
                               compact_capacity=N_V)
    res = stream.aggregate(
        agg, merge_every=2, fold_batch=2,
        checkpoint_path=ckpt_path, checkpoint_every=1,
        resume=os.path.exists(ckpt_path),
        codec_workers=2, h2d_depth=2,
    )
    labels = None
    for labels in res:
        if sleep_s:
            # Throttled consumer: the compress/H2D stages run ahead, so
            # the parent's SIGKILL lands with units in flight.
            time.sleep(sleep_s)
    save_checkpoint(out_path, np.asarray(labels), position=res.stats["chunks"])


if __name__ == "__main__":
    main(sys.argv[1:])
