"""Native parser, metrics, and prefetch utilities."""

import os

import numpy as np
import pytest

from gelly_tpu.utils.metrics import StageTimer, ThroughputMeter, metered
from gelly_tpu.utils.prefetch import prefetch


def test_prefetch_order_and_completion():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))
    assert list(prefetch(iter([]), depth=2)) == []
    assert list(prefetch(iter([1]), depth=0)) == [1]


def test_prefetch_propagates_exceptions():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_stage_timer_and_meter():
    t = StageTimer()
    with t("fold"):
        pass
    with t("fold"):
        pass
    rep = t.report()
    assert rep["fold"]["calls"] == 2
    m = ThroughputMeter()
    m.record(100)
    m.record(200)
    assert m.edges == 300


def test_stage_timer_reattribute():
    t = StageTimer()
    t.totals["ingest_compress"] = 2.0
    t.reattribute("ingest_compress", "codec_wait", 0.5)
    assert t.busy() == {"ingest_compress": 1.5, "codec_wait": 0.5}
    # Over-reattribution clamps src at zero (the wait is measured
    # independently of the stage clock, so rounding can exceed it).
    t.reattribute("ingest_compress", "codec_wait", 99.0)
    b = t.busy()
    assert b["ingest_compress"] == 0.0
    assert b["codec_wait"] == 99.5
    # Zero seconds still books the dst row: artifacts distinguish "no
    # wait" from "accounting not active". Negative is treated as zero.
    t2 = StageTimer()
    t2.reattribute("ingest_compress", "codec_wait", 0.0)
    t2.reattribute("ingest_compress", "codec_wait", -1.0)
    assert t2.busy() == {"ingest_compress": 0.0, "codec_wait": 0.0}
    assert t2.counts["codec_wait"] == 2


def test_throughput_meter_single_record_has_rate():
    # A single record() used to leave elapsed == 0 and report 0.0
    # edges/sec despite nonzero edges (ISSUE 5 satellite): the meter now
    # falls back to time-since-meter-creation for the one-sample case.
    import time as _t

    m = ThroughputMeter()
    _t.sleep(0.02)
    m.record(1000)
    assert m.edges == 1000
    assert m.elapsed >= 0.02
    assert m.edges_per_sec > 0.0
    snap = m.snapshot()
    assert snap["edges"] == 1000
    assert snap["edges_per_sec"] == round(m.edges_per_sec, 1) > 0
    assert snap["elapsed_s"] > 0


def test_throughput_meter_empty_and_multi_sample():
    m = ThroughputMeter()
    assert m.elapsed == 0.0 and m.edges_per_sec == 0.0  # no samples: no rate
    import time as _t

    m.record(100)
    _t.sleep(0.01)
    m.record(200)
    # Two samples: the ordinary first-to-last span, not the fallback.
    assert 0.01 <= m.elapsed < 10.0
    assert m.edges == 300


def test_throughput_meter_publishes_gauges():
    from gelly_tpu.obs import EventBus

    bus = EventBus()
    m = ThroughputMeter()
    m.record(50)
    m.publish(bus, prefix="t")
    snap = bus.snapshot()["gauges"]
    assert snap["t.edges"] == 50
    assert snap["t.edges_per_sec"] > 0


def test_trace_is_exception_safe(tmp_path, monkeypatch):
    # A body that raises must propagate ITS exception (never a masked
    # stop_trace error) and must always stop the started trace — no
    # dangling profiler session. The profiler is stubbed (a real CPU
    # start/stop cycle costs ~10s and tests nothing extra about OUR
    # wrapper); the real-profiler integration runs once in
    # test_trace_records_alignment_instants.
    import jax

    from gelly_tpu.utils.metrics import trace

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    with pytest.raises(RuntimeError, match="boom"):
        with trace(str(tmp_path / "t1")):
            raise RuntimeError("boom")
    assert calls == [("start", str(tmp_path / "t1")), ("stop",)]

    # A stop that itself fails must not MASK the body's exception.
    def bad_stop():
        calls.append(("stop",))
        raise ValueError("profiler stop failed")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    with pytest.raises(RuntimeError, match="body error"):
        with trace(str(tmp_path / "t2")):
            raise RuntimeError("body error")
    assert calls[-1] == ("stop",)


def test_trace_noops_when_profiler_unavailable(tmp_path, monkeypatch):
    import jax

    from gelly_tpu.utils.metrics import trace

    def broken_start(log_dir):
        raise RuntimeError("profiler unavailable on this platform")

    monkeypatch.setattr(jax.profiler, "start_trace", broken_start)
    ran = []
    with trace(str(tmp_path / "t")):
        ran.append(1)  # body still runs; no exception escapes
    assert ran == [1]


def test_trace_records_alignment_instants(tmp_path, monkeypatch):
    import jax

    from gelly_tpu.obs import SpanTracer
    from gelly_tpu.utils.metrics import trace

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    tr = SpanTracer()
    with trace(str(tmp_path / "t"), tracer=tr):
        pass
    names = [i["name"] for i in tr.instants()]
    assert names == ["jax_profiler_start", "jax_profiler_stop"]
    start = tr.instants("jax_profiler_start")[0]
    assert start["args"]["trace_id"] == tr.trace_id


@pytest.mark.slow  # real jax.profiler start/stop costs ~10s on CPU; the
# CI obs lane runs it, tier-1 relies on the stubbed wrapper tests above
def test_trace_real_profiler_roundtrip(tmp_path):
    from gelly_tpu.utils.metrics import trace

    with trace(str(tmp_path / "t1")):
        pass
    # No dangling session: a second trace starts cleanly.
    with pytest.raises(RuntimeError, match="boom"):
        with trace(str(tmp_path / "t2")):
            raise RuntimeError("boom")
    with trace(str(tmp_path / "t3")):
        pass


def test_overlap_stats_edge_cases():
    from gelly_tpu.utils.metrics import overlap_stats

    # Zero-wall window with busy stages: efficiency 0.0, never a crash.
    out = overlap_stats({"a": 1.0, "b": 2.0}, total_wall=0.0)
    assert out["overlap_efficiency"] == 0.0
    assert out["stage_busy_max_s"] == 2.0
    assert out["serial_stage_sum_s"] == 3.0
    # No stages at all (or all excluded): efficiency is None, sums zero.
    out = overlap_stats({}, total_wall=1.0)
    assert out["overlap_efficiency"] is None
    assert out["serial_stage_sum_s"] == 0.0
    out = overlap_stats({"total_wall": 5.0}, total_wall=5.0)
    assert out["overlap_efficiency"] is None  # excluded by default
    # Zero-busy stages: max 0 -> None efficiency (no divide).
    out = overlap_stats({"a": 0.0}, total_wall=0.0)
    assert out["overlap_efficiency"] is None


def test_stage_timer_reattribute_unknown_source():
    # Reattributing from a stage that never ran books the dst row and
    # leaves the (implicitly zero) src clamped at zero — artifacts show
    # the accounting was active even when the source stage is absent.
    t = StageTimer()
    t.reattribute("never_ran", "codec_wait", 1.5)
    b = t.busy()
    assert b["never_ran"] == 0.0
    assert b["codec_wait"] == 1.5
    assert t.counts["codec_wait"] == 1


def test_stage_timer_publish_gauges():
    from gelly_tpu.obs import EventBus

    bus = EventBus()
    t = StageTimer()
    t.totals["fold_dispatch"] = 1.25
    t.publish(bus)
    assert bus.snapshot()["gauges"]["stage.fold_dispatch.busy_s"] == 1.25


def test_metered_stream_counts_valid_edges(reference_edges):
    from gelly_tpu import edge_stream_from_edges

    s = edge_stream_from_edges(reference_edges, vertex_capacity=16, chunk_size=3)
    m = ThroughputMeter()
    n = sum(1 for _ in metered(iter(s), m))
    assert n == 3  # ceil(7/3) chunks
    assert m.edges == 7


def _native_available():
    try:
        from gelly_tpu.utils.native import _load

        _load()
        return True
    except Exception:
        return False


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_matches_python(tmp_path):
    from gelly_tpu.core.io import parse_edge_list_text
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "edges.txt"
    p.write_text(
        "% header\n1 2\n3\t4 9.5\n# comment\n  5 6\n\n-7 8\n"
        "9000000000 9000000001\n"
    )
    ns, nd = parse_edge_list_file(str(p))
    ps, pd, _ = parse_edge_list_text(p.read_text())
    np.testing.assert_array_equal(ns, ps)
    np.testing.assert_array_equal(nd, pd)
    # valued path
    ns2, nd2, nv = parse_edge_list_file(str(p), want_vals=True)
    assert nv[1] == 9.5 and nv[0] == 1.0


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_feeds_stream(tmp_path):
    from gelly_tpu import edge_stream_from_file

    p = tmp_path / "edges.txt"
    p.write_text("1 2\n2 3\n3 1\n")
    s = edge_stream_from_file(str(p), vertex_capacity=16, chunk_size=2)
    assert sorted((a, b) for a, b, _ in s.collect_edges()) == [
        (1, 2), (2, 3), (3, 1)
    ]


def test_aggregation_with_prefetch_matches(reference_edges):
    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.library.connected_components import (
        connected_components, labels_to_components,
    )

    edges = [(a, b) for a, b, _ in reference_edges] + [(6, 7), (8, 9)]
    expected = [[1, 2, 3, 4, 5], [6, 7], [8, 9]]
    for depth in (0, 3):
        s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=2)
        agg = connected_components(32)
        labels = s.aggregate(agg, merge_every=2, prefetch_depth=depth).result()
        assert labels_to_components(labels, s.ctx) == expected, depth


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_float_grammar_and_garbage(tmp_path):
    from gelly_tpu.core.io import parse_edge_list_text
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "edges.txt"
    p.write_text("1 2 1e3\n3 4 .5\n5 6 -0.25\n7 8x\n9 10 2.5e-2\n11 12\n")
    ns, nd, nv = parse_edge_list_file(str(p), want_vals=True)
    ps, pd, pv = parse_edge_list_text(p.read_text(), num_value_cols=1)
    np.testing.assert_array_equal(ns, ps)
    np.testing.assert_array_equal(nd, pd)
    np.testing.assert_allclose(nv, pv)
    assert nv.tolist() == [1000.0, 0.5, -0.25, 0.025, 1.0]


def test_prefetch_early_abandon_unblocks_worker():
    import threading
    import time as _t

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # abandon mid-stream
    _t.sleep(0.4)  # worker should notice the cancel and exit
    assert threading.active_count() <= before + 1
    assert len(produced) < 20  # source was not fully drained


def test_native_parser_overflow_reads_as_malformed(tmp_path):
    # An id wider than int64 must be skipped like any malformed line (the
    # python parser raises/skips), never silently wrapped to a wrong id.
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "ovf.txt"
    p.write_text(
        "1 2\n"
        "99999999999999999999999999 3\n"
        "4 170141183460469231731687303715884105727\n"
        "9223372036854775807 6\n"
        "-9223372036854775808 7\n"
        "-9223372036854775809 8\n"
    )
    src, dst = parse_edge_list_file(str(p))
    assert list(zip(src.tolist(), dst.tolist())) == [
        (1, 2),
        (9223372036854775807, 6),  # INT64_MAX parses
        (-9223372036854775808, 7),  # INT64_MIN parses (one past MAX)
    ]


def test_prefetch_preserves_worker_traceback():
    # The consumer-side re-raise must carry the SOURCE frame that failed,
    # not just the prefetch internals (satellite of the resilience PR).
    def gen():
        yield 1
        boom_line_marker = 1 / 0  # noqa: F841

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    import traceback

    try:
        next(it)
    except ZeroDivisionError as e:
        frames = traceback.extract_tb(e.__traceback__)
        assert any("boom_line_marker" in (f.line or "") for f in frames)
    else:
        raise AssertionError("expected ZeroDivisionError")


def test_prefetch_error_while_queue_full():
    # The source raises while the bounded queue is full and the consumer is
    # slow: the error wrapper must still get through (polling put), and the
    # already-queued items must be delivered first (order preserved).
    import time

    def gen():
        yield from range(4)
        raise RuntimeError("late failure")

    it = prefetch(gen(), depth=1)
    got = []
    time.sleep(0.3)  # let the worker fill the queue and hit the error path
    with pytest.raises(RuntimeError, match="late failure"):
        for x in it:
            got.append(x)
            time.sleep(0.05)  # keep the queue full behind us
    assert got == [0, 1, 2, 3]


def test_prefetch_cancel_while_queue_full():
    # Abandon the consumer while the queue is full; the worker must notice
    # the cancel and exit instead of blocking forever on its put.
    import threading
    import time

    def workers():
        # Only OUR named worker threads: asserting on the global
        # active_count() would flake when an unrelated runtime thread
        # (jax backend, another test's abandoned daemon) appears.
        return [t for t in threading.enumerate()
                if t.name.startswith("gelly-prefetch") and t.is_alive()]

    before = set(workers())
    pulled = []

    def gen():
        for i in range(10_000):
            pulled.append(i)
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # GeneratorExit -> finally -> cancel.set()
    deadline = time.monotonic() + 5.0
    while (set(workers()) - before) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not (set(workers()) - before)
    assert len(pulled) < 100  # worker stopped pulling from the source


def test_prefetch_map_cancel_while_queue_full():
    # A consumer that stops iterating early (explicit close) while the
    # bounded queue is FULL: the submitter must unblock from its parked
    # put and exit, queued-but-unstarted futures must be cancelled (their
    # fn never runs), and the worker pool must wind down — no thread
    # parked forever holding `depth` staged payloads.
    import threading
    import time

    from gelly_tpu.utils.prefetch import prefetch_map

    def submitters():
        return [t for t in threading.enumerate()
                if t.name.startswith("gelly-prefetch-submit")
                and t.is_alive()]

    before = set(submitters())
    pulled = []
    ran = []

    def src():
        for i in range(10_000):
            pulled.append(i)
            yield i

    def fn(x):
        ran.append(x)
        return x * 2

    it = prefetch_map(fn, src(), depth=2, workers=2)
    assert next(it) == 0
    time.sleep(0.3)  # let the submitter fill the queue and park on put
    it.close()  # GeneratorExit -> finally -> cancel + drain + shutdown
    deadline = time.monotonic() + 5.0
    while (set(submitters()) - before) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not (set(submitters()) - before)  # submitter exited
    n_after_close = len(ran)
    time.sleep(0.3)
    # Cancelled futures never run their fn after the close.
    assert len(ran) == n_after_close
    assert len(pulled) < 100  # source was not drained


def test_prefetch_map_external_cancel_unblocks_parked_consumer():
    # A generator can only be close()d between items, so when ANOTHER
    # thread (the executor's H2D leg) is parked inside __next__ waiting
    # on a stalled source, nothing can deliver GeneratorExit to it.
    # Setting the external cancel event must end the parked get within
    # one poll — the stream terminates, the submitter exits, and the
    # stalled source is never pulled again.
    import threading
    import time

    from gelly_tpu.utils.prefetch import prefetch_map

    release = threading.Event()
    cancel = threading.Event()
    pulled = []

    def src():
        pulled.append(0)
        yield 0
        release.wait(10)  # a source stuck on I/O
        for i in range(1, 100):
            pulled.append(i)
            yield i

    it = prefetch_map(lambda x: x * 2, src(), depth=2, workers=1,
                      cancel=cancel)
    got = []

    def consume():
        got.extend(it)  # parks in __next__ on the stalled source

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [0]  # consumer is now parked waiting for item 1
    cancel.set()
    t.join(2.0)
    assert not t.is_alive()  # the parked get noticed the event
    assert got == [0]
    release.set()
    time.sleep(0.3)
    # The submitter finishes at most the one pull it was already parked
    # on, then notices the cancel — the source is never drained.
    assert len(pulled) <= 2


def test_prefetch_map_external_cancel_with_fast_source():
    # The cancel event must end the stream even when the source is FAST:
    # the queue is then never empty, so a cancel check only on the
    # empty-queue path would never run and the generator would keep
    # yielding until exhaustion — the documented "setting the event ends
    # the stream" contract requires a per-iteration check.
    import itertools
    import threading

    from gelly_tpu.utils.prefetch import prefetch_map

    cancel = threading.Event()
    it = prefetch_map(lambda x: x, itertools.count(), depth=4, workers=1,
                      cancel=cancel)
    got = []
    for v in it:
        got.append(v)
        if len(got) == 10:
            cancel.set()  # same-thread set: next pull must terminate
    assert got == list(range(10))


def test_prefetch_map_error_while_queue_full():
    import time

    from gelly_tpu.utils.prefetch import prefetch_map

    def src():
        yield from range(4)
        raise RuntimeError("submitter failure")

    it = prefetch_map(lambda x: x * 2, src(), depth=1, workers=2)
    got = []
    time.sleep(0.3)
    with pytest.raises(RuntimeError, match="submitter failure"):
        for x in it:
            got.append(x)
            time.sleep(0.05)
    assert got == [0, 2, 4, 6]


def test_restartable_prefetch_reopens_at_next_undelivered():
    from gelly_tpu.utils.prefetch import restartable_prefetch

    opens = []
    fail_once = {"armed": True}

    def make_iter(pos):
        opens.append(pos)

        def gen():
            for i in range(pos, 10):
                if i == 6 and fail_once["armed"]:
                    fail_once["armed"] = False
                    raise OSError("flaky source")
                yield i

        return gen()

    out = list(restartable_prefetch(make_iter, depth=3,
                                    should_restart=lambda e: True))
    assert out == list(range(10))  # exactly once each
    assert opens[0] == 0 and len(opens) == 2
    # The restart reopened at the next UNDELIVERED index — nothing lost
    # even though the queue held prefetched items when the worker died.
    assert opens[1] <= 6


def test_restartable_prefetch_bounded_restarts():
    from gelly_tpu.utils.prefetch import restartable_prefetch

    def make_iter(pos):
        def gen():
            yield pos
            raise OSError("always down")

        return gen()

    it = restartable_prefetch(make_iter, depth=1, max_restarts=3,
                              should_restart=lambda e: True)
    with pytest.raises(OSError, match="always down"):
        list(it)


def test_restartable_prefetch_respects_should_restart():
    from gelly_tpu.utils.prefetch import restartable_prefetch

    def make_iter(pos):
        def gen():
            yield from range(pos, 3)
            raise ValueError("permanent")

        return gen()

    it = restartable_prefetch(make_iter, depth=1,
                              should_restart=lambda e: False)
    with pytest.raises(ValueError, match="permanent"):
        list(it)


@pytest.mark.racecheck
def test_stage_timer_report_concurrent_with_new_stages():
    """Regression (racecheck RC003 class): report() used to iterate the
    LIVE totals dict — a prefetch worker booking its first sample into a
    NEW stage mid-report raised "dictionary changed size during
    iteration". The snapshot-under-lock fix must survive a hammering."""
    import threading

    timer = StageTimer()
    stop = threading.Event()
    errs = []

    def worker(wid):
        i = 0
        try:
            while not stop.is_set():
                # i cycles so the stage set keeps gaining NEW names (the
                # mid-iteration insert the bug needs) without growing
                # unboundedly — report() stays O(stages) per call.
                with timer(f"stage-{wid}-{i % 64}"):
                    pass
                i += 1
        except BaseException as e:  # pragma: no cover - the regression
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            rep = timer.report()
            for row in rep.values():
                assert row["calls"] >= 1  # totals/counts never skewed
            timer.busy()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert errs == []
