"""Native parser, metrics, and prefetch utilities."""

import os

import numpy as np
import pytest

from gelly_tpu.utils.metrics import StageTimer, ThroughputMeter, metered
from gelly_tpu.utils.prefetch import prefetch


def test_prefetch_order_and_completion():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))
    assert list(prefetch(iter([]), depth=2)) == []
    assert list(prefetch(iter([1]), depth=0)) == [1]


def test_prefetch_propagates_exceptions():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_stage_timer_and_meter():
    t = StageTimer()
    with t("fold"):
        pass
    with t("fold"):
        pass
    rep = t.report()
    assert rep["fold"]["calls"] == 2
    m = ThroughputMeter()
    m.record(100)
    m.record(200)
    assert m.edges == 300


def test_metered_stream_counts_valid_edges(reference_edges):
    from gelly_tpu import edge_stream_from_edges

    s = edge_stream_from_edges(reference_edges, vertex_capacity=16, chunk_size=3)
    m = ThroughputMeter()
    n = sum(1 for _ in metered(iter(s), m))
    assert n == 3  # ceil(7/3) chunks
    assert m.edges == 7


def _native_available():
    try:
        from gelly_tpu.utils.native import _load

        _load()
        return True
    except Exception:
        return False


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_matches_python(tmp_path):
    from gelly_tpu.core.io import parse_edge_list_text
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "edges.txt"
    p.write_text(
        "% header\n1 2\n3\t4 9.5\n# comment\n  5 6\n\n-7 8\n"
        "9000000000 9000000001\n"
    )
    ns, nd = parse_edge_list_file(str(p))
    ps, pd, _ = parse_edge_list_text(p.read_text())
    np.testing.assert_array_equal(ns, ps)
    np.testing.assert_array_equal(nd, pd)
    # valued path
    ns2, nd2, nv = parse_edge_list_file(str(p), want_vals=True)
    assert nv[1] == 9.5 and nv[0] == 1.0


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_feeds_stream(tmp_path):
    from gelly_tpu import edge_stream_from_file

    p = tmp_path / "edges.txt"
    p.write_text("1 2\n2 3\n3 1\n")
    s = edge_stream_from_file(str(p), vertex_capacity=16, chunk_size=2)
    assert sorted((a, b) for a, b, _ in s.collect_edges()) == [
        (1, 2), (2, 3), (3, 1)
    ]


def test_aggregation_with_prefetch_matches(reference_edges):
    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.library.connected_components import (
        connected_components, labels_to_components,
    )

    edges = [(a, b) for a, b, _ in reference_edges] + [(6, 7), (8, 9)]
    expected = [[1, 2, 3, 4, 5], [6, 7], [8, 9]]
    for depth in (0, 3):
        s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=2)
        agg = connected_components(32)
        labels = s.aggregate(agg, merge_every=2, prefetch_depth=depth).result()
        assert labels_to_components(labels, s.ctx) == expected, depth


@pytest.mark.skipif(not _native_available(), reason="no native toolchain")
def test_native_parser_float_grammar_and_garbage(tmp_path):
    from gelly_tpu.core.io import parse_edge_list_text
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "edges.txt"
    p.write_text("1 2 1e3\n3 4 .5\n5 6 -0.25\n7 8x\n9 10 2.5e-2\n11 12\n")
    ns, nd, nv = parse_edge_list_file(str(p), want_vals=True)
    ps, pd, pv = parse_edge_list_text(p.read_text(), num_value_cols=1)
    np.testing.assert_array_equal(ns, ps)
    np.testing.assert_array_equal(nd, pd)
    np.testing.assert_allclose(nv, pv)
    assert nv.tolist() == [1000.0, 0.5, -0.25, 0.025, 1.0]


def test_prefetch_early_abandon_unblocks_worker():
    import threading
    import time as _t

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # abandon mid-stream
    _t.sleep(0.4)  # worker should notice the cancel and exit
    assert threading.active_count() <= before + 1
    assert len(produced) < 20  # source was not fully drained


def test_native_parser_overflow_reads_as_malformed(tmp_path):
    # An id wider than int64 must be skipped like any malformed line (the
    # python parser raises/skips), never silently wrapped to a wrong id.
    from gelly_tpu.utils.native import parse_edge_list_file

    p = tmp_path / "ovf.txt"
    p.write_text(
        "1 2\n"
        "99999999999999999999999999 3\n"
        "4 170141183460469231731687303715884105727\n"
        "9223372036854775807 6\n"
        "-9223372036854775808 7\n"
        "-9223372036854775809 8\n"
    )
    src, dst = parse_edge_list_file(str(p))
    assert list(zip(src.tolist(), dst.tolist())) == [
        (1, 2),
        (9223372036854775807, 6),  # INT64_MAX parses
        (-9223372036854775808, 7),  # INT64_MIN parses (one past MAX)
    ]
