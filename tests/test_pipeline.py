"""Pipelined executor (engine/aggregation.py): parity, faults, resume.

The executor overlaps host compress (K workers), H2D transfer (dedicated
double-buffer thread) and the donated device folds — none of which may
change a single bit of any emission. This suite pins that down on
adversarial streams (hot vertex, deletions, cap overflow), drives the new
codec/H2D fault boundaries, and proves the last-retired-chunk checkpoint
rule with chunks in flight (generator abandon + subprocess kill -9).
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine import faults
from gelly_tpu.library.connected_components import connected_components

N_V = 256


def _zipf_edges(n=800, seed=3, n_v=N_V):
    rng = np.random.default_rng(seed)
    return [
        (int(a), int(b))
        for a, b in zip(rng.zipf(1.4, n) % n_v, rng.zipf(1.4, n) % n_v)
    ]


def _stream(edges, chunk_size=64, n_v=N_V):
    return edge_stream_from_edges(
        [(a, b, 1.0) for a, b in edges],
        vertex_capacity=n_v, chunk_size=chunk_size,
    )


def _run_cc(edges, codec, *, serial, merge_mode="auto", **kw):
    s = _stream(edges)
    agg = connected_components(N_V, merge="gather", codec=codec,
                               merge_mode=merge_mode)
    if serial:
        kw.update(ingest_workers=0, prefetch_depth=0, h2d_depth=0)
    else:
        kw.setdefault("codec_workers", 3)
        kw.setdefault("h2d_depth", 2)
    res = s.aggregate(agg, merge_every=8, fold_batch=8, **kw)
    return np.asarray(res.result()), res


# ---------------------------------------------------------------------- #
# parity: pipelined == serial, bit for bit


@pytest.mark.parametrize("codec", ["sparse", "compact"])
def test_pipelined_parity_hot_vertex(codec):
    # Zipf streams put a mega-degree vertex in every chunk — maximum
    # contention for the donated fold state and the ordered codec
    # session, folded through all three overlapped stages.
    edges = _zipf_edges()
    base, _ = _run_cc(edges, codec, serial=True)
    pipe, _ = _run_cc(edges, codec, serial=False)
    assert np.array_equal(base, pipe)


@pytest.mark.parametrize("codec", ["sparse", "compact"])
def test_pipelined_parity_delta_merge(codec):
    # merge_mode="delta" (dirty-row gather) must emit the same labels as
    # the replicated merge, through the pipelined executor — for BOTH
    # plan families: the sparse plan's vertex-space delta and the compact
    # plan's cid-space delta (croot union + vertex_of max-scatter).
    edges = _zipf_edges(seed=11)
    rep, _ = _run_cc(edges, codec, serial=True, merge_mode="replicated")
    delta, res = _run_cc(edges, codec, serial=False, merge_mode="delta")
    assert np.array_equal(rep, delta)
    assert res.stats["merge_modes"]["delta"] > 0


def test_codec_wait_reattributed_from_compress_stage():
    # The ordered compact session's await_turn blocks INSIDE the
    # ingest_compress timer context; at teardown the engine reclassifies
    # that wait into a codec_wait stage so the bench's serial-cost
    # comparison (pipeline_serial_sum_s) counts work, not lock-wait.
    edges = _zipf_edges()
    _, res = _run_cc(edges, "compact", serial=False)
    busy = res.timer.busy()
    # Booked even at 0.0 wait: artifacts distinguish "no contention"
    # from "accounting not active".
    assert busy["codec_wait"] >= 0.0
    assert busy["ingest_compress"] >= 0.0
    # The sparse codec has no ordered session: no reclassification row.
    _, res_sparse = _run_cc(edges, "sparse", serial=False)
    assert "codec_wait" not in res_sparse.timer.busy()


def test_pipelined_parity_deletions():
    # EDGE_DELETION events ride the raw-chunk path (batch folds +
    # donation, no codec): a deletion-honoring count fold must retire
    # every event exactly once regardless of pipelining.
    import jax.numpy as jnp

    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.engine.aggregation import SummaryAggregation

    rng = np.random.default_rng(7)
    n = 640
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    events = (rng.random(n) < 0.25).astype(np.int8)  # 1 = deletion

    def agg():
        return SummaryAggregation(
            init=lambda: jnp.zeros((), jnp.int64),
            fold=lambda s, c: s + jnp.sum(
                jnp.where(c.valid, jnp.where(c.event == 1, -1, 1), 0)
            ),
            combine=lambda a, b: a + b,
        )

    def run(**kw):
        s = edge_stream_from_source(
            EdgeChunkSource(src, dst, events=events, chunk_size=64,
                            table=IdentityVertexTable(N_V)),
            N_V,
        )
        return int(s.aggregate(agg(), merge_every=4, fold_batch=4,
                               **kw).result())

    want = int((events == 0).sum()) - int((events == 1).sum())
    assert run(ingest_workers=0, prefetch_depth=0, h2d_depth=0) == want
    assert run(codec_workers=2, h2d_depth=2) == want


def test_pipelined_cap_overflow_fails_loudly():
    # Compact-space overflow raised inside a codec WORKER must surface at
    # the consumer promptly (no hang, ordered-session turns released) on
    # both the serial and pipelined paths.
    from gelly_tpu.ops.compact_space import CompactSpaceOverflow

    edges = _zipf_edges(seed=5)

    def run(**kw):
        s = _stream(edges)
        agg = connected_components(N_V, merge="gather", codec="compact",
                                   compact_capacity=8)  # << touched
        return s.aggregate(agg, merge_every=8, fold_batch=8, **kw).result()

    with pytest.raises(CompactSpaceOverflow):
        run(ingest_workers=0, prefetch_depth=0, h2d_depth=0)
    with pytest.raises(CompactSpaceOverflow):
        run(codec_workers=3, h2d_depth=2)


def test_accum_plan_emissions_survive_donation():
    # Accumulate plans WITHOUT a transform yield the live fold state —
    # donation must stay off for them, or the next fold deletes the
    # consumer's held emission (review finding: degree_aggregate on one
    # shard raised 'Array has been deleted' on any retained emission).
    from gelly_tpu.library.degrees import degree_aggregate
    from gelly_tpu.parallel import mesh as mesh_lib

    edges = _zipf_edges(n=96, seed=2)
    s = _stream(edges, chunk_size=16)
    agg = degree_aggregate(N_V, ingest_combine=False)
    m1 = mesh_lib.make_mesh(1)  # S=1: the accumulate-plan shape
    emissions = list(s.aggregate(agg, mesh=m1, merge_every=2))
    assert len(emissions) >= 2
    # Every retained emission stays readable and monotone in total degree.
    totals = [int(np.asarray(e).sum()) for e in emissions]
    assert totals == sorted(totals)
    assert totals[-1] == 2 * len(edges)


# ---------------------------------------------------------------------- #
# knobs


def test_codec_workers_alias_rejects_both():
    s = _stream(_zipf_edges(n=64))
    agg = connected_components(N_V)
    with pytest.raises(ValueError, match="codec_workers or ingest_workers"):
        s.aggregate(agg, codec_workers=2, ingest_workers=2).result()


def test_h2d_depth_validation():
    s = _stream(_zipf_edges(n=64))
    agg = connected_components(N_V)
    with pytest.raises(ValueError, match="h2d_depth"):
        s.aggregate(agg, h2d_depth=-1).result()


def test_merge_mode_validation_and_plan_cache_key():
    with pytest.raises(ValueError, match="merge_mode"):
        connected_components(N_V, merge_mode="nope")
    # Rebinding merge_mode on the same instance must re-jit (cache keys
    # on it, like fold_backend), not silently reuse stale executables.
    edges = _zipf_edges(n=128)
    agg = connected_components(N_V, merge="gather", codec="sparse",
                               merge_mode="replicated")
    a = np.asarray(_stream(edges).aggregate(agg, merge_every=4).result())
    agg.merge_mode = "delta"
    b = np.asarray(_stream(edges).aggregate(agg, merge_every=4).result())
    assert np.array_equal(a, b)
    assert len(agg._plan_cache) == 2
    # Misconfigured plans fail LOUDLY at plan time, not with a TypeError
    # from inside a jit trace at the first window close: merge_mode=delta
    # needs the plan's merge_delta, and merge_delta needs its bucket-sizing
    # merge_dirty_count.
    bad = connected_components(N_V, merge="gather", codec="sparse",
                               merge_mode="delta")
    bad.merge_delta = None
    with pytest.raises(ValueError, match="no merge_delta"):
        _stream(edges).aggregate(bad, merge_every=4).result()
    bad2 = connected_components(N_V, merge="gather", codec="sparse",
                                merge_mode="delta")
    bad2.merge_dirty_count = None
    with pytest.raises(ValueError, match="merge_dirty_count"):
        _stream(edges).aggregate(bad2, merge_every=4).result()
    # The ENGINE validates the mode too (hand-built SummaryAggregation
    # plans bypass the library's resolve_merge_mode): a typo'd mode must
    # not silently run the capacity-proportional replicated merge.
    bad3 = connected_components(N_V, merge="gather", codec="sparse")
    bad3.merge_mode = "Delta"  # case typo, set after construction
    with pytest.raises(ValueError, match="merge_mode must be"):
        _stream(edges).aggregate(bad3, merge_every=4).result()


# ---------------------------------------------------------------------- #
# fault injection at the new executor boundaries

pytest_faults = pytest.mark.faults


@pytest_faults
def test_codec_worker_fault_propagates():
    # A fault in a codec WORKER (ordered compact session in play) must
    # propagate to the consumer as the injected error — not wedge the
    # pool behind an unreleased assignment turn.
    edges = _zipf_edges(seed=9)
    s = _stream(edges)
    agg = connected_components(N_V, merge="gather", codec="compact",
                               compact_capacity=N_V)
    plan = faults.FaultPlan([faults.Fault(boundary="codec", at=1)])
    with faults.install(plan):
        with pytest.raises(faults.FaultInjected):
            s.aggregate(agg, merge_every=8, fold_batch=8,
                        codec_workers=2, h2d_depth=2).result()
    assert plan.fired and plan.fired[0][0] == "codec"
    # The pool unwound: a fresh run on the same aggregation completes.
    got = np.asarray(
        _stream(edges).aggregate(agg, merge_every=8, fold_batch=8,
                                 codec_workers=2, h2d_depth=2).result()
    )
    base = np.asarray(
        _stream(edges).aggregate(agg, merge_every=8, fold_batch=8,
                                 ingest_workers=0, prefetch_depth=0,
                                 h2d_depth=0).result()
    )
    assert np.array_equal(got, base)


@pytest_faults
def test_h2d_fault_propagates():
    edges = _zipf_edges(seed=10)
    s = _stream(edges)
    agg = connected_components(N_V, merge="gather", codec="sparse")
    plan = faults.FaultPlan([faults.Fault(boundary="h2d", at=2)])
    with faults.install(plan):
        with pytest.raises(faults.FaultInjected):
            s.aggregate(agg, merge_every=4, fold_batch=2,
                        codec_workers=2, h2d_depth=2).result()
    assert ("h2d", 2, "raise") in plan.fired


# ---------------------------------------------------------------------- #
# exactly-once resume with chunks in flight


def test_resume_with_inflight_double_buffers(tmp_path):
    # Abandon the pipelined run mid-stream with units sitting in the
    # compress/H2D buffers; resume must refold exactly the un-retired
    # suffix (last-retired-chunk rule) — final labels identical to an
    # uninterrupted run.
    p = str(tmp_path / "ck.npz")
    edges = _zipf_edges(seed=21)

    def make(resume):
        s = _stream(edges, chunk_size=32)
        agg = connected_components(N_V, merge="gather", codec="compact",
                                   compact_capacity=N_V)
        return s.aggregate(agg, merge_every=8, fold_batch=8,
                           checkpoint_path=p, checkpoint_every=1,
                           resume=resume, codec_workers=2, h2d_depth=2)

    it = iter(make(False))
    next(it)
    next(it)
    it.close()  # chunks in flight in the compress/H2D stages are dropped
    assert os.path.exists(p)
    got = np.asarray(make(True).result())
    s = _stream(edges, chunk_size=32)
    agg = connected_components(N_V, merge="gather", codec="compact",
                               compact_capacity=N_V)
    want = np.asarray(s.aggregate(agg, merge_every=8, fold_batch=8,
                                  ingest_workers=0, prefetch_depth=0,
                                  h2d_depth=0).result())
    assert np.array_equal(got, want)


CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_pipeline_crash_child.py")


def _spawn(ckpt, out, sleep_s):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single default CPU device is enough
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(out), str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest_faults
def test_pipelined_kill9_resume_bit_identical(tmp_path):
    from gelly_tpu.engine.checkpoint import load_checkpoint

    ckpt = tmp_path / "pipe-ck.npz"
    out_clean = tmp_path / "clean.npz"
    out_resumed = tmp_path / "resumed.npz"

    p = _spawn(tmp_path / "clean-ck.npz", out_clean, 0.0)
    assert p.wait(timeout=300) == 0

    # Throttled run: SIGKILL once a checkpoint is durably on disk — the
    # pipeline guarantees staged units are in flight past the recorded
    # position at that moment.
    p = _spawn(ckpt, out_resumed, 0.05)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if p.poll() is not None:
            pytest.fail(f"child exited early (rc={p.returncode})")
        if ckpt.exists():
            break
        time.sleep(0.02)
    else:
        pytest.fail("no checkpoint appeared before the deadline")
    os.kill(p.pid, signal.SIGKILL)
    assert p.wait(timeout=60) == -signal.SIGKILL
    assert not out_resumed.exists()

    _, pos, _ = load_checkpoint(str(ckpt))
    import _pipeline_crash_child as child

    total = -(-child.N_EDGES // child.CHUNK)
    assert 0 < pos < total  # mid-stream position

    p = _spawn(ckpt, out_resumed, 0.0)
    assert p.wait(timeout=300) == 0
    resumed, _, _ = load_checkpoint(str(out_resumed))
    clean, _, _ = load_checkpoint(str(out_clean))
    assert len(resumed) == len(clean) == 1
    assert resumed[0].tobytes() == clean[0].tobytes()
