"""Serving-plane telemetry: the STATS endpoint, e2e watermarks, and
their exactly-once interplay (ISSUE 14 acceptance).

The headline test interleaves STATS requests with a live DATA stream
feeding the REAL engine serve path, SIGKILLs the server mid-stream and
proves (a) the STATS replies are valid JSON carrying per-stream
backlog-age watermarks and p50/p99 for the fold-dispatch /
checkpoint-write / receive→stage histograms, (b) the interleaving never
perturbed DATA sequencing — the resumed run's non-idempotent degree
fold lands bit-identical to the oracle (exactly-once), and (c) the
watermark ledger never publishes a negative or time-travelling backlog
age, re-seeding from the RESUMED POSITION after the crash.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gelly_tpu import obs
from gelly_tpu.ingest import IngestClient, IngestServer
from gelly_tpu.ingest.client import edge_payload
from gelly_tpu.obs.status import build_stats, fetch_stats

N_V = 128

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_telemetry_crash_child.py")


# --------------------------------------------------------------------- #
# STATS endpoint basics (fast, in-process)


def _start_server(**kw):
    return IngestServer(port=0, **kw).start()


def test_stats_dedicated_connection_never_disturbs_data_stream():
    """A stats-only connection is answered mid-stream and never adopted
    as the data connection: the in-flight DATA stream keeps its socket,
    its sequence, and its acks."""
    with obs.scope() as bus, obs.record_metrics():
        srv = _start_server()
        try:
            cli = IngestClient("127.0.0.1", srv.port).connect()
            rng = np.random.default_rng(5)
            for _ in range(3):
                cli.send(edge_payload(rng.integers(0, N_V, 8),
                                      rng.integers(0, N_V, 8)))
            cli.flush(timeout=30)
            st = fetch_stats("127.0.0.1", srv.port)
            assert st["server"]["next_seq"] == 3
            assert st["counters"]["ingest.data_frames_raw"] == 3
            assert "stream" in st["watermarks"]
            assert st["histograms"]["ingest.receive_to_stage_ms"][
                "count"] == 3
            for q in ("p50", "p90", "p99", "max"):
                assert st["histograms"]["ingest.receive_to_stage_ms"][
                    q] >= 0
            # The data stream is alive and sequenced AFTER the stats
            # read: more frames flow and ack on the same connection.
            for _ in range(2):
                cli.send(edge_payload(rng.integers(0, N_V, 8),
                                      rng.integers(0, N_V, 8)))
            assert cli.flush(timeout=30) == 5
            assert srv.next_seq == 5
            assert bus.snapshot()["counters"][
                "ingest.stats_requests"] == 1
            cli.close(flush_timeout=None)
        finally:
            srv.stop()


def test_client_stats_interleaves_on_the_data_connection():
    with obs.scope(), obs.record_metrics():
        srv = _start_server()
        try:
            cli = IngestClient("127.0.0.1", srv.port).connect()
            rng = np.random.default_rng(6)
            cli.send(edge_payload(rng.integers(0, N_V, 8),
                                  rng.integers(0, N_V, 8)))
            st = cli.stats()
            assert st["server"]["next_seq"] == 1
            assert st["recording"] is True
            # Sequencing untouched: the next DATA frame is seq 1.
            assert cli.send(edge_payload(rng.integers(0, N_V, 8),
                                         rng.integers(0, N_V, 8))) == 1
            assert cli.flush(timeout=30) == 2
            cli.close(flush_timeout=None)
        finally:
            srv.stop()


def test_stats_fields_extras_and_failure_containment():
    calls = {"n": 0}

    def fields():
        calls["n"] += 1
        if calls["n"] == 1:
            return {"custom": {"answer": 42}}
        raise RuntimeError("stats provider broke")

    with obs.scope():
        srv = _start_server(stats_fields=fields)
        try:
            st = fetch_stats("127.0.0.1", srv.port)
            assert st["custom"] == {"answer": 42}
            # A raising provider is contained, reported in-band, and
            # the stream/server stays up.
            st2 = fetch_stats("127.0.0.1", srv.port)
            assert "stats provider broke" in st2["stats_fields_error"]
        finally:
            srv.stop()


def test_status_cli_prints_snapshot(capsys):
    with obs.scope():
        srv = _start_server()
        try:
            from gelly_tpu.obs import status as status_mod

            rc = status_mod.main([f"127.0.0.1:{srv.port}"])
            assert rc == 0
            out = json.loads(capsys.readouterr().out)
            assert out["server"]["port"] == srv.port
            assert "counters" in out and "watermarks" in out
            assert status_mod.main(["not-a-target"]) == 2
        finally:
            srv.stop()


def test_build_stats_shape_is_json_ready():
    with obs.scope() as bus, obs.record_metrics():
        bus.inc("ingest.frames_received")
        bus.observe("engine.fold_dispatch_ms", 1.5)
        bus.watermarks.stamp("stream", 0)
        st = json.loads(json.dumps(build_stats(bus)))
    assert st["counters"]["ingest.frames_received"] == 1
    assert st["histograms"]["engine.fold_dispatch_ms"]["count"] == 1
    assert st["watermarks"]["stream"]["pending"] == 1
    assert "process_index" in st["host"]


# --------------------------------------------------------------------- #
# tenant engine telemetry through the router


@pytest.mark.tenants
def test_tenant_router_wires_engine_telemetry_into_stats(tmp_path):
    from gelly_tpu.engine.tenants import MultiTenantEngine
    from gelly_tpu.ingest import TenantRouter
    from gelly_tpu.library.connected_components import cc_tenant_tier

    with obs.scope() as bus, obs.record_metrics():
        agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
        eng = MultiTenantEngine(merge_every=1).start()
        router = TenantRouter(eng, "small", vertex_capacity=N_V)
        eng.add_tier("small", agg, cap)
        srv = _start_server()
        try:
            router.attach(srv)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            rng = np.random.default_rng(11)
            for t in (3, 4):
                for _ in range(2):
                    p = edge_payload(rng.integers(0, N_V, 8),
                                     rng.integers(0, N_V, 8))
                    p["tenant"] = np.array([t], np.int64)
                    cli.send(p)
            cli.flush(timeout=30)
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    if eng.position(3) >= 2 and eng.position(4) >= 2:
                        break
                except KeyError:
                    pass
                time.sleep(0.02)
            st = fetch_stats("127.0.0.1", srv.port)
            assert set(st["tenants"]) >= {"3", "4"}
            for tid in ("3", "4"):
                row = st["tenants"][tid]
                assert row["position"] >= 2
                assert row["backlog_age_s"] >= 0.0
                assert row["tier"] == "small"
            # Per-tenant e2e histograms + the round histogram recorded.
            snap = bus.snapshot()
            assert "tenants.round_ms" in snap["histograms"]
            assert "tenants.t3.e2e_ingress_to_fold_ms" in snap[
                "histograms"]
            assert snap["gauges"]["tenants.backlog_age_max_s"] >= 0.0
            cli.close(flush_timeout=None)
        finally:
            srv.stop()
            router.stop()
            eng.stop()


def test_router_attach_rekeys_preattach_wire_stamps():
    """Regression: frames staged between server.start() and
    router.attach() are ingress-stamped under the server's DEFAULT
    watermark key; attach must carry those stamps into the re-keyed
    wire ledger so the drain loop's retirement reaches them — left
    behind, max_backlog_age() grows forever for a phantom stream."""
    from gelly_tpu.engine.tenants import MultiTenantEngine
    from gelly_tpu.ingest import TenantRouter
    from gelly_tpu.library.connected_components import cc_tenant_tier

    with obs.scope() as bus, obs.record_metrics():
        agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
        eng = MultiTenantEngine(merge_every=1).start()
        router = TenantRouter(eng, "small", vertex_capacity=N_V)
        eng.add_tier("small", agg, cap)
        srv = _start_server()
        try:
            # DATA lands BEFORE attach: stamped under the default key.
            cli = IngestClient("127.0.0.1", srv.port).connect()
            rng = np.random.default_rng(17)
            p = edge_payload(rng.integers(0, N_V, 8),
                             rng.integers(0, N_V, 8))
            p["tenant"] = np.array([5], np.int64)
            cli.send(p)
            cli.flush(timeout=30)
            assert bus.watermarks.snapshot()["stream"]["pending"] == 1
            router.attach(srv)
            # The stamp moved with the key...
            assert "stream" not in bus.watermarks.snapshot()
            wire_key = srv.watermark_stream
            # ...and the drain loop retires it as the frame routes.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if bus.watermarks.snapshot().get(
                        wire_key, {}).get("pending") == 0:
                    break
                time.sleep(0.02)
            assert bus.watermarks.snapshot()[wire_key]["pending"] == 0
            assert bus.watermarks.max_backlog_age() == pytest.approx(
                0.0, abs=60.0)  # sane, not a phantom epoch-sized age
            cli.close(flush_timeout=None)
        finally:
            srv.stop()
            router.stop()
            eng.stop()


def test_client_stats_rejects_straggler_reply():
    """Regression: a straggler reply to an earlier TIMED-OUT stats()
    call must not satisfy a later call with a stale snapshot — the
    request token in the frame seq is matched on the reply."""
    from gelly_tpu.ingest import wire

    cli = IngestClient("127.0.0.1", 1)
    sent: list = []

    def fake_send(frame):
        _ftype, seq, _len, _crc = wire.unpack_header(frame)
        sent.append(seq)
        # Deliver a STALE straggler synchronously (the reply the
        # previous, timed-out request would have gotten)...
        with cli._lock:
            cli._stats_payload = b'{"which": "stale"}'
            cli._stats_reply_token = seq - 1
        cli._stats_evt.set()
        # ...and the REAL reply shortly after, like the reader thread.

        def late():
            time.sleep(0.15)
            with cli._lock:
                cli._stats_payload = b'{"which": "fresh"}'
                cli._stats_reply_token = seq
            cli._stats_evt.set()

        threading.Thread(target=late, daemon=True).start()

    cli._raw_send = fake_send
    got = cli.stats(timeout=5.0)
    assert got == {"which": "fresh"}
    assert len(sent) == 1

    # A reply that never matches the token times out instead of
    # returning stale data.
    def stale_only(frame):
        _ftype, seq, _len, _crc = wire.unpack_header(frame)
        with cli._lock:
            cli._stats_payload = b'{"which": "stale"}'
            cli._stats_reply_token = seq - 1
        cli._stats_evt.set()

    cli._raw_send = stale_only
    from gelly_tpu.ingest.client import IngestError

    with pytest.raises(IngestError, match="no STATS reply"):
        cli.stats(timeout=0.3)


def test_tenant_submit_stamp_survives_mid_dispatch_submit():
    """Regression: submit-side stamp positions used ``consumed +
    len(queue)``, which under-counts by one inside the scheduler's
    pop-queue→bump-consumed window (two separate lock acquisitions) —
    a submit landing there collided with the previous chunk's stamp
    and its e2e sample was silently dropped. Positions now come from a
    monotonic per-tenant ``submitted`` counter."""
    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.engine.tenants import MultiTenantEngine
    from gelly_tpu.library.connected_components import cc_tenant_tier

    def chunks(seed, n=3):
        rng = np.random.default_rng(seed)
        stream = edge_stream_from_edges(
            [(int(a), int(b)) for a, b in rng.integers(0, N_V, (n * 8, 2))],
            vertex_capacity=N_V, chunk_size=8,
        )
        return list(stream)[:n]

    with obs.scope() as bus, obs.record_metrics():
        agg, cap = cc_tenant_tier(N_V, chunk_capacity=8)
        eng = MultiTenantEngine(merge_every=1)  # scheduler NOT running
        eng.add_tier("small", agg, cap)
        eng.admit(7, "small")
        c1, c2, c3 = chunks(31)
        eng.submit(7, c1)
        eng.submit(7, c2)
        # Emulate the dispatch window: the chunk is popped but
        # ``consumed`` has not been bumped yet.
        with eng._lock:
            eng._tenants[7].queue.popleft()
        eng.submit(7, c3)  # must stamp position 2, not re-stamp 1
        snap = bus.watermarks.snapshot()["7"]
        assert snap["pending"] == 3, snap
        assert snap["oldest_position"] == 0


def test_sharded_provider_watermarks_fully_retire(tmp_path):
    """Regression: provider unit seqs are lane-interleaved
    (``local_unit * shards + shard``), so deriving stamp positions as
    ``seq * batch`` overshot the positions retirement ever reaches —
    after the run drained, the leaked stamps read as permanent
    backlog. Provider-path stamps draw dense positions instead; the
    ledger must be EMPTY once the stream completes, fresh and
    resumed."""
    from gelly_tpu.engine.checkpoint import load_checkpoint
    from gelly_tpu.ingest import (
        edge_stream_from_sharded_file,
        write_binary_edges,
    )
    from gelly_tpu.library.connected_components import (
        connected_components,
    )

    rng = np.random.default_rng(23)
    src = rng.integers(0, N_V, 900)
    dst = rng.integers(0, N_V, 900)
    path = str(tmp_path / "edges.bin")
    write_binary_edges(path, src, dst)

    def agg_stream(ck, resume):
        stream = edge_stream_from_sharded_file(path, N_V, shards=3,
                                               chunk_size=64)
        return stream.aggregate(
            connected_components(N_V), merge_every=4, fold_batch=2,
            source_provider=True, checkpoint_path=ck,
            checkpoint_every=1, resume=resume,
        )

    # Fresh run to completion: the ledger must drain completely.
    ck1 = str(tmp_path / "ck_fresh.npz")
    with obs.scope() as bus, obs.record_metrics():
        labels = np.asarray(agg_stream(ck1, resume=False).result())
        snap = bus.watermarks.snapshot()["stream"]
        assert snap["pending"] == 0, snap
        assert bus.watermarks.backlog_age("stream") == 0.0
        h = bus.snapshot()["histograms"]
        # Every chunk's e2e latency was observed — none stranded.
        assert h["engine.e2e_ingress_to_durable_ms"]["count"] == snap[
            "base"] > 0

    # Abandon a second run mid-stream, then resume: skip_until > 0
    # must not re-offset the stamp positions.
    ck2 = str(tmp_path / "ck_resume.npz")
    it = iter(agg_stream(ck2, resume=False))
    for _ in range(2):
        next(it)
    it.close()
    _, pos, _ = load_checkpoint(ck2)
    assert pos > 0
    with obs.scope() as bus, obs.record_metrics():
        labels2 = np.asarray(agg_stream(ck2, resume=True).result())
        snap = bus.watermarks.snapshot()["stream"]
        assert snap["pending"] == 0, snap
        assert bus.watermarks.backlog_age("stream") == 0.0
    np.testing.assert_array_equal(labels, labels2)


def test_coordinated_runner_watermarks_fully_retire(tmp_path):
    """Regression: the coordinated checkpoint path published epochs but
    never retired the e2e ledger (the local ``_checkpoint`` did, and
    the end-of-stream drain hid behind an ``elif`` the coordinator
    branch shadowed) — with telemetry on, a healthy multi-host run
    accumulated one stamp per chunk forever and backlog_age grew
    without bound. Every barrier commit is a durability point: the
    ledger must drain and the ingress→durable histogram populate."""
    from gelly_tpu.engine.coordination import (
        CoordinationConfig,
        Coordinator,
        HostIdentity,
    )
    from gelly_tpu.engine.resilience import (
        ResilienceConfig,
        ResilientRunner,
    )

    co = Coordinator(
        str(tmp_path / "store"), HostIdentity(0, 1),
        CoordinationConfig(lease_ttl=2.0, poll_s=0.005,
                           barrier_timeout=10.0, lease_thread=False),
    )
    with obs.scope() as bus, obs.record_metrics():
        r = ResilientRunner(
            lambda s, c: (s + np.int64(c), None), list(range(10)),
            np.int64(0), coordinator=co,
            config=ResilienceConfig(checkpoint_every_chunks=4,
                                    watchdog_timeout=30.0),
        )
        assert int(r.run()) == sum(range(10))
        assert r.stats["checkpoints"] == 3  # 4, 8, final 10
        snap = bus.watermarks.snapshot()["stream"]
        assert snap["pending"] == 0, snap
        assert snap["base"] == 10
        assert bus.watermarks.backlog_age("stream") == 0.0
        h = bus.snapshot()["histograms"]
        assert h["resilience.e2e_ingress_to_durable_ms"]["count"] == 10
        assert bus.gauges["engine.backlog_age_s"] == 0.0


# --------------------------------------------------------------------- #
# the headline: STATS interleaved with DATA + SIGKILL exactly-once +
# watermark correctness across resume (slow; CI obs lane)


def _spawn_child(ckpt, port_file, out, sleep_s):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(port_file), str(out),
         str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _wait_port(port_file, proc, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"child exited rc={proc.returncode} before publishing "
                "its port"
            )
        if os.path.exists(port_file):
            return int(open(port_file).read())
        time.sleep(0.02)
    raise AssertionError("child never published its port")


@pytest.mark.slow
@pytest.mark.faults
def test_stats_mid_stream_sigkill_exactly_once_and_watermarks(tmp_path):
    import _telemetry_crash_child as child_mod

    rng = np.random.default_rng(41)
    total = 32  # multiple of the child's merge window
    payloads = [
        edge_payload(rng.integers(0, child_mod.N_V, child_mod.CHUNK),
                     rng.integers(0, child_mod.N_V, child_mod.CHUNK))
        for _ in range(total)
    ]
    # Degrees oracle: every edge bumps out-deg[src] and in-deg[dst]
    # (the ±1 scatter is non-idempotent — a double-folded acked chunk
    # is visible in the final vector).
    golden = np.zeros(child_mod.N_V, dtype=np.int64)
    for p in payloads:
        golden += np.bincount(p["src"], minlength=child_mod.N_V)
        golden += np.bincount(p["dst"], minlength=child_mod.N_V)

    ckpt = str(tmp_path / "ck.npz")
    port_file = str(tmp_path / "port")
    out = str(tmp_path / "final.npz")

    p1 = _spawn_child(ckpt, port_file, out, 0.05)
    port = _wait_port(port_file, p1)
    cli = IngestClient("127.0.0.1", port, send_pause_timeout=60)
    cli.connect()

    sent = 0
    stats_seen: list = []

    def sender():
        nonlocal sent
        from gelly_tpu.ingest.client import IngestError

        while sent < total:
            try:
                cli.send(payloads[sent])
                sent += 1
            except IngestError:
                sent += 1  # buffered; reconnect() delivers it
                return

    t = threading.Thread(target=sender, daemon=True)
    t.start()

    # Interleave STATS with the live DATA stream (dedicated conn) and
    # hold the acceptance bar on the reply: valid JSON, per-stream
    # backlog watermark, and p50/p99 for the three named histograms.
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if cli.acked >= 4:
            st = fetch_stats("127.0.0.1", port, timeout=10)
            stats_seen.append(st)
            hists = st["histograms"]
            if ("engine.fold_dispatch_ms" in hists
                    and "engine.checkpoint_write_ms" in hists
                    and "ingest.receive_to_stage_ms" in hists):
                break
        time.sleep(0.05)
    else:
        pytest.fail("histograms never appeared in mid-stream STATS")
    st = stats_seen[-1]
    for name in ("engine.fold_dispatch_ms", "engine.checkpoint_write_ms",
                 "ingest.receive_to_stage_ms"):
        h = st["histograms"][name]
        assert h["count"] >= 1
        assert h["p50"] >= 0.0 and h["p99"] >= h["p50"] >= 0.0
    assert "stream" in st["watermarks"]
    assert st["watermarks"]["stream"]["backlog_age_s"] >= 0.0
    assert st["server"]["auto_ack"] is False

    # SIGKILL mid-stream, with acked-but-unsent work outstanding.
    acked_before_kill = cli.acked
    assert acked_before_kill < total
    os.kill(p1.pid, signal.SIGKILL)
    assert p1.wait(timeout=60) == -signal.SIGKILL
    assert not os.path.exists(out)
    t.join(timeout=60)

    # Restart: the new incarnation resumes at its newest checkpoint —
    # the STATS interleaving above must not have perturbed sequencing.
    os.unlink(port_file)
    p2 = _spawn_child(ckpt, port_file, out, 0.0)
    cli.port = _wait_port(port_file, p2)
    deadline = time.monotonic() + 60
    while True:
        try:
            cli.reconnect()
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    assert cli.acked >= acked_before_kill  # acked work never rewinds
    while sent < total:
        cli.send(payloads[sent])
        sent += 1
    cli.flush(timeout=180)
    cli.close()
    assert p2.wait(timeout=300) == 0

    from gelly_tpu.engine.checkpoint import load_checkpoint

    final, pos, meta = load_checkpoint(out)
    # Flat leaves arrive in sorted-key order: ages, degrees, oldest.
    ages, degrees, oldest = final
    assert pos == total
    # THE exactly-once assertion: the non-idempotent degree vector is
    # bit-identical to the oracle — no acked chunk double-folded, no
    # chunk lost, STATS notwithstanding.
    np.testing.assert_array_equal(np.asarray(degrees), golden)
    # Watermark correctness across the SIGKILL: no negative and no
    # wall-clock-sized (time-travelling) backlog age, in either
    # incarnation's samples.
    assert np.all(np.asarray(ages) >= 0.0)
    assert np.all(np.asarray(ages) < 600.0)
    # The resumed incarnation re-seeded from the RESUMED POSITION: its
    # samples never report a pending stamp below it.
    assert meta["resumed"] is True
    resume_pos = int(meta["resume_pos"])
    assert resume_pos >= acked_before_kill
    sampled = np.asarray(oldest)
    sampled = sampled[sampled >= 0]
    if sampled.size:
        assert int(sampled.min()) >= resume_pos


# --------------------------------------------------------------------- #
# watermark min-deque: O(1)-amortized backlog_age vs the ledger scan
# (hammer/parity regression for the perf fix — the gauge read used to
# be an O(pending) min() over the stamp dict under the shared lock)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _scan_age(wm, stream, now):
    """The reference implementation the deque replaced: one O(pending)
    min-scan over the raw ledger."""
    st = wm._streams.get(stream)
    if st is None or not st.stamps:
        return 0.0
    return max(0.0, now - min(st.stamps.values()))


def test_watermark_minq_parity_hammer_vs_scan():
    from gelly_tpu.obs.watermarks import Watermarks

    rng = np.random.default_rng(7)
    ck = _FakeClock()
    wm = Watermarks(clock=ck)
    streams = ["a", "b"]
    base = {s: 0 for s in streams}
    nxt = {s: 0 for s in streams}
    reads = 0
    for _ in range(4000):
        ck.t += float(rng.random()) * 0.01
        s = streams[int(rng.integers(0, 2))]
        nxt[s] = max(nxt[s], base[s])
        op = float(rng.random())
        if op < 0.55:
            if rng.random() < 0.05 and nxt[s] > base[s]:
                p = int(rng.integers(base[s], nxt[s]))  # out-of-order
            else:
                p = nxt[s]
                nxt[s] += 1
            wm.stamp(s, p)
        elif op < 0.72:
            upto = int(rng.integers(base[s], nxt[s] + 2))
            wm.retire_durable(s, upto)
            base[s] = max(base[s], upto)
        elif op < 0.82:
            wm.retire_fold(s, int(rng.integers(base[s], nxt[s] + 2)))
        elif op < 0.90:
            pos = int(rng.integers(base[s], nxt[s] + 2))
            wm.seed(s, pos)
            base[s] = max(base[s], pos)
        else:
            reads += 1
            now = ck.t
            assert wm.backlog_age(s) == pytest.approx(
                _scan_age(wm, s, now), abs=1e-12)
            want = max((_scan_age(wm, x, now) for x in streams),
                       default=0.0)
            assert wm.max_backlog_age() == pytest.approx(want, abs=1e-12)
    assert reads > 200  # the hammer actually exercised the read path


def test_watermark_minq_rekey_and_snapshot_parity():
    from gelly_tpu.obs.watermarks import Watermarks

    ck = _FakeClock()
    wm = Watermarks(clock=ck)
    for p, t in [(0, 1.0), (1, 2.0), (2, 3.0)]:
        ck.t = t
        wm.stamp("pre", p)
    ck.t = 4.0
    wm.stamp("dst", 1)
    wm.rekey("pre", "dst")  # arbitrary-order merge -> lazy rebuild
    ck.t = 10.0
    assert wm.backlog_age("dst") == pytest.approx(9.0)
    assert wm.backlog_age("pre") == 0.0
    assert wm.snapshot()["dst"]["backlog_age_s"] == pytest.approx(9.0)
    wm.retire_durable("dst", 2)
    assert wm.backlog_age("dst") == pytest.approx(7.0)
    wm.retire_durable("dst", 100)
    assert wm.backlog_age("dst") == 0.0
    assert wm.max_backlog_age() == 0.0


def test_watermark_minq_in_order_reads_never_rebuild():
    from gelly_tpu.obs.watermarks import Watermarks

    ck = _FakeClock()
    wm = Watermarks(clock=ck)
    for p in range(512):
        ck.t += 0.001
        wm.stamp("s", p)
        if p % 7 == 0:
            wm.backlog_age("s")
        if p % 64 == 63:
            wm.retire_durable("s", p - 32)
    st = wm._streams["s"]
    # The hot path stays incremental: in-position-order traffic never
    # flips the dirty bit (no O(n log n) rebuild), and the deque never
    # outgrows the ledger — each entry is pushed once and popped once.
    assert st.dirty is False
    assert len(st.minq) <= len(st.stamps)
    assert wm.backlog_age("s") == pytest.approx(
        _scan_age(wm, "s", ck.t), abs=1e-12)
