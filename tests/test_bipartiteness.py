"""Bipartiteness parity tests against the reference's vectors
(T/example/test/BipartitenessCheckTest.java) plus parity-union-find unit
coverage and multi-shard merge behavior."""

import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.library.bipartiteness import bipartiteness_check, to_candidates
from gelly_tpu.ops import parity_unionfind as puf
from gelly_tpu.parallel import mesh as mesh_lib

# BipartitenessCheckTest.getBipartiteEdges (:73-82)
BIPARTITE = [(1, 2), (1, 3), (1, 4), (4, 5), (4, 7), (4, 9)]
# BipartitenessCheckTest.getNonBipartiteEdges (:84-93) — contains 1-2-3 cycle
NON_BIPARTITE = [(1, 2), (2, 3), (3, 1), (4, 5), (5, 7), (4, 1)]


def run(edges, merge_every=2, chunk_size=2, **kw):
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=chunk_size)
    agg = bipartiteness_check(16)
    res = s.aggregate(agg, merge_every=merge_every, **kw).result()
    return res, s.ctx


def test_bipartite_graph_golden():
    res, ctx = run(BIPARTITE)
    ok, comps = to_candidates(res, ctx)
    assert ok is True
    # Golden: one component rooted at 1 with signs
    # {1:T, 2:F, 3:F, 4:F, 5:T, 7:T, 9:T} (BipartitenessCheckTest.java:40-44).
    assert comps == {1: {1: True, 2: False, 3: False, 4: False,
                         5: True, 7: True, 9: True}}


def test_non_bipartite_collapses():
    res, ctx = run(NON_BIPARTITE)
    assert to_candidates(res, ctx) == (False, {})


def test_failure_is_sticky_across_windows():
    # Odd cycle arrives early; later clean edges must not clear the flag.
    edges = [(1, 2), (2, 3), (3, 1)] + [(10 + i, 20 + i) for i in range(6)]
    res, _ = run(edges, merge_every=1, chunk_size=2)
    assert not bool(res.ok)


def test_two_disjoint_components_colorings():
    res, ctx = run([(1, 2), (2, 3), (5, 6)])
    ok, comps = to_candidates(res, ctx)
    assert ok
    assert comps == {1: {1: True, 2: False, 3: True}, 5: {5: True, 6: False}}


def test_multi_shard_merge(devices):
    # Cross-partition odd cycle: each shard's local fold may be clean; only
    # the collective merge exposes the conflict (Candidates.merge parity).
    m = mesh_lib.make_mesh(8)
    cyc = [(i, i + 1) for i in range(8)] + [(8, 0)]  # 9-cycle: odd
    s = edge_stream_from_edges(cyc, vertex_capacity=16, chunk_size=1)
    res = s.aggregate(bipartiteness_check(16), mesh=m, merge_every=9).result()
    assert not bool(res.ok)

    even = [(i, i + 1) for i in range(7)] + [(7, 0)]  # 8-cycle: even
    s2 = edge_stream_from_edges(even, vertex_capacity=16, chunk_size=1)
    res2 = s2.aggregate(bipartiteness_check(16), mesh=m, merge_every=8).result()
    assert bool(res2.ok)


# ---------------- parity union-find unit tests ---------------- #


def test_union_parity_conflict_detection():
    f = puf.fresh_parity_forest(8)
    u = jnp.array([0, 1, 2], dtype=jnp.int32)
    v = jnp.array([1, 2, 0], dtype=jnp.int32)  # triangle
    q = jnp.ones(3, jnp.int32)
    f = puf.union_edges_parity(f, u, v, q, jnp.ones(3, bool))
    assert bool(f.failed)


def test_union_parity_even_cycle_ok():
    f = puf.fresh_parity_forest(8)
    u = jnp.array([0, 1, 2, 3], dtype=jnp.int32)
    v = jnp.array([1, 2, 3, 0], dtype=jnp.int32)  # 4-cycle
    f = puf.union_edges_parity(f, u, v, jnp.ones(4, jnp.int32),
                               jnp.ones(4, bool))
    assert not bool(f.failed)
    labels, colors = puf.two_coloring(f, jnp.ones(8, bool))
    assert colors[0] == colors[2] and colors[1] == colors[3]
    assert colors[0] != colors[1]


def test_merge_forests_detects_cross_conflict():
    # Path 0-1-2 in forest A; edge 0-2 in forest B; union is an odd... no —
    # 0-1-2 plus 0-2 is a triangle: odd cycle.
    a = puf.fresh_parity_forest(8)
    a = puf.union_edges_parity(
        a, jnp.array([0, 1], jnp.int32), jnp.array([1, 2], jnp.int32),
        jnp.ones(2, jnp.int32), jnp.ones(2, bool))
    b = puf.fresh_parity_forest(8)
    b = puf.union_edges_parity(
        b, jnp.array([0], jnp.int32), jnp.array([2], jnp.int32),
        jnp.ones(1, jnp.int32), jnp.ones(1, bool))
    merged = puf.merge_parity_forests(a, b)
    assert bool(merged.failed)


def test_merge_stack_matches_pairwise():
    import numpy.random as npr
    rng = np.random.default_rng(3)
    forests = []
    for k in range(4):
        f = puf.fresh_parity_forest(16)
        u = jnp.asarray(rng.integers(0, 16, 6), jnp.int32)
        v = jnp.asarray(rng.integers(0, 16, 6), jnp.int32)
        f = puf.union_edges_parity(f, u, v, jnp.ones(6, jnp.int32),
                                   jnp.ones(6, bool))
        forests.append(f)
    stacked = puf.ParityForest(
        parent=jnp.stack([f.parent for f in forests]),
        rel=jnp.stack([f.rel for f in forests]),
        failed=jnp.stack([f.failed for f in forests]),
    )
    via_stack = puf.merge_parity_stack(stacked)
    via_pairs = forests[0]
    for f in forests[1:]:
        via_pairs = puf.merge_parity_forests(via_pairs, f)
    assert bool(via_stack.failed) == bool(via_pairs.failed)
    if not bool(via_stack.failed):
        seen = jnp.ones(16, bool)
        l1, c1 = puf.two_coloring(via_stack, seen)
        l2, c2 = puf.two_coloring(via_pairs, seen)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
