"""gelly_tpu.analysis.plancheck: compiled-plan contract checker.

Every PC rule is exercised BOTH ways — a seeded-violation fixture that
must flag (line-anchored) and a clean fixture proving the rule's
exemption paths (refusal-scope knob reads, the assignment-chain chase
into the cache key, the rebind idiom, snapshot-through-copy, the
identity carry, axis-derived masks, the full refusal set). Each
historical bug class is re-seeded: the typo'd-``merge_mode`` stale-plan
class against the REAL ``_compiled_plan`` key (PR 4), the
snapshot-aliases-donated-buffer class (PR 10), the masked-lane drift
class (PR 12), and an entry point stripped of its ``stack_ordered``
refusal against the real ``fuse`` (PC4xx). Suppression scoping, the
repo tip (the ISSUE 15 acceptance gate), and the CLI satellites —
shared single-parse AST cache, ``--changed``, ``--format=github``, and
the unparseable-file robustness contract (loud per-file ``SRC001`` from
every tool, never a crash, never a silent skip) — are covered with
exit-code assertions through the unified CLI."""

import json
import os
import subprocess
import textwrap

import pytest

from gelly_tpu.analysis import loader, plancheck
from gelly_tpu.analysis.__main__ import main as analysis_main

pytestmark = pytest.mark.plancheck

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
AGG_PY = os.path.join(REPO, "gelly_tpu", "engine", "aggregation.py")
MQ_PY = os.path.join(REPO, "gelly_tpu", "engine", "multiquery.py")


def _lint_files(tmp_path, files):
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        if isinstance(src, bytes):
            p.write_bytes(src)
        else:
            p.write_text(src)
        paths.append(str(p))
    return plancheck.lint_paths(str(tmp_path), paths)


def _lint_src(tmp_path, src, name="fixture_mod.py"):
    return _lint_files(tmp_path, {name: src})


def _line_of(src, marker):
    for i, line in enumerate(src.splitlines(), 1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


# --------------------------------------------------------------------- #
# repo tip (ISSUE 15 acceptance: zero unsuppressed findings, and the
# discovery passes the tip-clean assertion rests on are not vacuous)

def test_plancheck_clean_on_repo_tip():
    findings = plancheck.lint_paths(REPO, [os.path.join(REPO, "gelly_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_tip_builder_and_knob_discovery_not_vacuous():
    # The tip-clean assertion above proves nothing if no builder was
    # discovered or the knob universe came up empty: the checker must
    # have found the real memoized builders and classified the real
    # SummaryAggregation fields.
    c = plancheck.PlanChecker(REPO)
    c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    agg_mod = [m for p, m in c._modules.items()
               if p.endswith(os.path.join("engine", "aggregation.py"))][0]
    builders = {b.fn.name for b in c._find_builders(agg_mod)}
    assert {"_compiled_plan", "_compiled_tenant_plan"} <= builders
    assert {"merge_mode", "fold_backend", "merge_degree", "transient",
            "jit_transform", "transform_may_alias",
            "stack_ordered"} <= c._scalar_knobs
    assert {"merge_mode", "fold_backend"} <= c._str_knobs
    assert {"init", "fold", "combine", "host_compress"} \
        <= c._callable_fields


def test_tip_refusal_matrix_entry_points_all_resolve():
    # Every REFUSAL_MATRIX row names a real (module, function): a rename
    # that forgot the table would flag PC402 on tip — assert the matrix
    # is non-trivial and fully resolved (tip-clean covers the rest).
    assert len(plancheck.REFUSAL_MATRIX) >= 6
    assert sum(len(rows) for rows in plancheck.REFUSAL_MATRIX.values()) \
        >= 15
    c = plancheck.PlanChecker(REPO)
    findings = c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    assert [f for f in findings if f.rule == "PC402"] == []
    linted_bases = {os.path.basename(p) for p in c._modules}
    for base, _fn in plancheck.REFUSAL_MATRIX:
        assert base in linted_bases, base


# --------------------------------------------------------------------- #
# shared fixture pieces

AGG_SRC = textwrap.dedent('''\
    import dataclasses
    from typing import Any, Callable


    @dataclasses.dataclass
    class SummaryAggregation:
        name: str
        init: Callable[[], Any]
        fold: Callable[[Any, Any], Any]
        fold_backend: str = "jit"
        merge_mode: str = "tree"
        merge_degree: int = 8
        transient: bool = False
        jit_transform: bool = True


''')

# --------------------------------------------------------------------- #
# PC101: the PR 4 merge_mode bug class — a knob the builder reads but
# the cache key does not carry. The fold_backend read inside the
# if-raise refusal is the documented exemption (reads that only feed a
# refusal need no keying), and doubles as its PC102 validation.

PC101_SRC = AGG_SRC + textwrap.dedent('''\
    def _compiled_plan(agg, mesh):
        key = (tuple(mesh.axis_names), agg.fold_backend, agg.merge_degree)
        per = agg.__dict__.setdefault("_plan_cache", {})
        if key in per:
            return per[key]
        if agg.fold_backend not in ("jit", "pallas"):
            raise ValueError("unknown fold_backend")
        mode = agg.merge_mode                            # M-PC101
        def fold_chunk(state, chunk):
            return agg.fold(state, chunk)
        plan = (fold_chunk, mode)
        per[key] = plan
        return plan
''')


def test_pc101_unkeyed_knob_flags_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, PC101_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC101", _line_of(PC101_SRC, "M-PC101"))], \
        "\n".join(f.render() for f in findings)
    assert "merge_mode" in findings[0].message
    assert findings[0].hint


def test_pc101_keyed_knob_is_clean(tmp_path):
    # Keying merge_mode fixes PC101; as a str key knob it then needs
    # its own allowed-set validation (PC102), provided by a sibling.
    src = PC101_SRC.replace(
        "key = (tuple(mesh.axis_names), agg.fold_backend, agg.merge_degree)",
        "key = (tuple(mesh.axis_names), agg.fold_backend,\n"
        "           agg.merge_mode, agg.merge_degree)")
    findings = _lint_files(tmp_path, {"fixture_mod.py": src,
                                      "validators.py": VALIDATOR_SRC})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc101_read_inside_a_refusal_is_exempt(tmp_path):
    # Dropping the unkeyed read leaves only the refusal-scoped
    # fold_backend read and the keyed ones: exempt, clean.
    src = PC101_SRC.replace(
        "    mode = agg.merge_mode                            # M-PC101\n",
        "")
    src = src.replace("plan = (fold_chunk, mode)", "plan = (fold_chunk,)")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc101_real_compiled_plan_key_drop_flips(tmp_path):
    # The PR 4 bug class re-seeded against the REAL builder: drop
    # agg.merge_mode from _compiled_plan's key tuple and the checker
    # must flag the builder's surviving merge_mode reads.
    with open(AGG_PY) as f:
        src = f.read()
    needle = "agg.fold_backend, agg.merge_mode, agg.merge_degree,"
    assert needle in src, "the _compiled_plan key line moved — re-anchor"
    mutated = src.replace(
        needle, "agg.fold_backend, agg.merge_degree,")
    got = _lint_src(tmp_path, mutated, name="aggregation.py")
    pc101 = [f for f in got if f.rule == "PC101"]
    assert pc101 and all("merge_mode" in f.message for f in pc101), \
        "\n".join(f.render() for f in got)
    # control: the unmodified file carries no PC101 (single-file lint
    # may raise package-scoped PC102 noise; PC101 is the re-seed).
    clean = _lint_src(tmp_path, src, name="aggregation.py")
    assert [f for f in clean if f.rule == "PC101"] == [], \
        "\n".join(f.render() for f in clean)


# --------------------------------------------------------------------- #
# PC102: a str-typed key knob with no allowed-set membership check in
# the whole package — the typo that silently selects the wrong plan.

PC102_SRC = AGG_SRC + textwrap.dedent('''\
    def _compiled_plan(agg, mesh):
        key = (tuple(mesh.axis_names), agg.merge_mode)   # M-PC102
        per = agg.__dict__.setdefault("_plan_cache", {})
        if key in per:
            return per[key]
        def fold_chunk(state, chunk):
            return agg.fold(state, chunk)
        plan = (fold_chunk,)
        per[key] = plan
        return plan
''')

VALIDATOR_SRC = textwrap.dedent('''\
    def resolve_merge_mode(agg):
        if agg.merge_mode not in ("tree", "delta"):
            raise ValueError("unknown merge_mode: " + agg.merge_mode)
        return agg.merge_mode
''')


def test_pc102_unvalidated_str_knob_flags_at_the_key(tmp_path):
    findings = _lint_src(tmp_path, PC102_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC102", _line_of(PC102_SRC, "M-PC102"))], \
        "\n".join(f.render() for f in findings)
    assert "merge_mode" in findings[0].message


def test_pc102_sibling_module_validation_is_clean(tmp_path):
    # "Validated SOMEWHERE in the package": the resolve_merge_mode
    # pattern in a sibling module satisfies the rule.
    findings = _lint_files(tmp_path, {"fixture_mod.py": PC102_SRC,
                                      "validators.py": VALIDATOR_SRC})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc102_inactive_on_a_partial_package_subset(tmp_path):
    # A sibling module on disk but NOT in the lint set means "validated
    # nowhere" may be under-collection: PC102 must stay silent (the
    # OB002 precedent).
    (tmp_path / "validators.py").write_text(VALIDATOR_SRC)
    p = tmp_path / "fixture_mod.py"
    p.write_text(PC102_SRC)
    findings = plancheck.lint_paths(str(tmp_path), [str(p)])
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC103: a builder parameter (mesh, lane width, ...) read by the plan
# but unreachable from the key — a plan compiled for another width.

PC103_SRC = AGG_SRC + textwrap.dedent('''\
    def _compiled_plan(agg, mesh, width):
        key = (tuple(mesh.axis_names), agg.merge_degree)  # M-PC103
        per = agg.__dict__.setdefault("_plan_cache", {})
        if key in per:
            return per[key]
        rows = width * agg.merge_degree
        def fold_chunk(state, chunk):
            return agg.fold(state, chunk)
        plan = (fold_chunk, rows)
        per[key] = plan
        return plan
''')


def test_pc103_unkeyed_parameter_flags_at_the_key(tmp_path):
    findings = _lint_src(tmp_path, PC103_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC103", _line_of(PC103_SRC, "M-PC103"))], \
        "\n".join(f.render() for f in findings)
    assert "'width'" in findings[0].message


def test_pc103_refusal_only_parameter_is_exempt(tmp_path):
    # A parameter whose only read feeds a refusal guard needs no
    # keying (the PC101 exemption, applied symmetrically).
    src = PC103_SRC.replace(
        "    rows = width * agg.merge_degree\n",
        "    if width is None:\n"
        "        raise ValueError(\"width is required\")\n")
    src = src.replace("plan = (fold_chunk, rows)", "plan = (fold_chunk,)")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc103_assignment_chain_into_the_key_is_clean(tmp_path):
    # `lanes = (width, ...)` then `key = (lanes, ...)`: the coverage
    # chase follows simple assignment chains into the key tuple.
    src = PC103_SRC.replace(
        "key = (tuple(mesh.axis_names), agg.merge_degree)  # M-PC103",
        "lanes = (width, tuple(mesh.axis_names))\n"
        "    key = (lanes, agg.merge_degree)")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC201: the PR 10 bug class — a snapshot path inside a donating
# builder returning the live state instead of an eager copy.

PC201_SRC = textwrap.dedent('''\
    import jax
    import jax.numpy as jnp


    def _fold(state, chunk):
        return state


    def _compiled_plan(agg, mesh):
        key = (agg.fold_backend, agg.merge_degree)
        per = agg.__dict__.setdefault("_plan_cache", {})
        if key in per:
            return per[key]
        fold_chunk = jax.jit(_fold, donate_argnums=(0,))
        def snapshot(state):                             # M-PC201
            return state
        plan = (fold_chunk, snapshot)
        per[key] = plan
        return plan
''')


def test_pc201_snapshot_without_copy_flags(tmp_path):
    findings = _lint_src(tmp_path, PC201_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC201", _line_of(PC201_SRC, "M-PC201"))], \
        "\n".join(f.render() for f in findings)
    assert "'snapshot'" in findings[0].message


def test_pc201_eager_copy_is_clean(tmp_path):
    src = PC201_SRC.replace(
        "        return state\n    plan",
        "        return jax.tree.map(jnp.copy, state)\n    plan")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc201_inactive_without_donation(tmp_path):
    # The same bare-return snapshot in a NON-donating builder is the
    # documented cheap path (no buffer is ever deleted) — clean.
    src = PC201_SRC.replace(", donate_argnums=(0,)", "")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC202: a donated fold called outside the rebind idiom keeps a
# poisoned reference (TPU-only 'Array has been deleted', invisible on
# the CPU test tier).

PC202_SRC = textwrap.dedent('''\
    def serve(agg, mesh, chunks, sink):
        plan = _compiled_plan(agg, mesh)
        state, fold_chunk = plan
        for chunk in chunks:
            sink.append(fold_chunk(state, chunk))        # M-PC202
        return state
''')


def test_pc202_unrebound_fold_call_flags(tmp_path):
    findings = _lint_src(tmp_path, PC202_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC202", _line_of(PC202_SRC, "M-PC202"))], \
        "\n".join(f.render() for f in findings)


def test_pc202_rebind_idiom_is_clean(tmp_path):
    src = textwrap.dedent('''\
        def serve(agg, mesh, chunks, sink):
            plan = _compiled_plan(agg, mesh)
            state, fold_chunk = plan
            for chunk in chunks:
                state = fold_chunk(state, chunk)
                sink.append(plan.snapshot(state))
            return state
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc202_attribute_receiver_both_polarities(tmp_path):
    # `<x>.plan.fold(...)` is donated wherever it appears: the bare
    # call flags, the rebound call two lines down stays clean.
    src = textwrap.dedent('''\
        def step(batch, state, chunk, out):
            out.result = batch.plan.fold(state, chunk)   # M-PC202-ATTR
            state = batch.plan.fold(state, chunk)
            return state
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC202", _line_of(src, "M-PC202-ATTR"))], \
        "\n".join(f.render() for f in findings)


def test_pc202_rebinding_the_name_clears_donation(tmp_path):
    # `fold_chunk = identity` shadows the donated binding: calls after
    # the rebind are ordinary calls, not donation sites.
    src = textwrap.dedent('''\
        def serve(agg, mesh, chunk, sink):
            plan = _compiled_plan(agg, mesh)
            state, fold_chunk = plan
            fold_chunk = make_plain_fold(agg)
            sink.append(fold_chunk(state, chunk))
            return state
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC203: publishing the live donated state to a snapshot/latest slot —
# queries then read buffers the next dispatch invalidates.

PC203_SRC = textwrap.dedent('''\
    def serve(agg, mesh, chunk, store):
        plan = _compiled_plan(agg, mesh)
        state, fold_chunk = plan
        store.latest_summary = state                     # M-PC203
        state = fold_chunk(state, chunk)
        return state
''')


def test_pc203_live_state_publication_flags(tmp_path):
    findings = _lint_src(tmp_path, PC203_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC203", _line_of(PC203_SRC, "M-PC203"))], \
        "\n".join(f.render() for f in findings)
    assert "latest_summary" in findings[0].message


def test_pc203_snapshot_call_is_clean(tmp_path):
    src = PC203_SRC.replace(
        "store.latest_summary = state                     # M-PC203",
        "store.latest_summary = plan.snapshot(state)")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc203_alias_hop_does_not_launder(tmp_path):
    # `snap = state; store.latest = snap` — the chase follows the
    # simple-assignment hop back to the live expression.
    src = textwrap.dedent('''\
        def serve(agg, mesh, chunk, store):
            plan = _compiled_plan(agg, mesh)
            state, fold_chunk = plan
            snap = state
            store.latest_summary = snap                  # M-HOP
            state = fold_chunk(state, chunk)
            return state
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC203", _line_of(src, "M-HOP"))], \
        "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC301/PC302: the PR 12 bug class — masked no-op lanes must carry the
# original leaf bit-unchanged, under a mask derived from the lane axis.

PC301_SRC = textwrap.dedent('''\
    import jax
    import jax.numpy as jnp


    def masked_fold(state, new, mask):
        return jax.tree.map(
            lambda s, n: jnp.where(mask, n, jnp.zeros_like(s)),  # M-PC301
            state, new)
''')


def test_pc301_non_identity_false_branch_flags(tmp_path):
    findings = _lint_src(tmp_path, PC301_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC301", _line_of(PC301_SRC, "M-PC301"))], \
        "\n".join(f.render() for f in findings)
    assert "jnp.zeros_like(s)" in findings[0].message


def test_pc301_identity_carry_is_clean(tmp_path):
    src = PC301_SRC.replace(
        "jnp.where(mask, n, jnp.zeros_like(s)),  # M-PC301",
        "jnp.where(mask, n, s),")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc301_arithmetic_on_the_carry_flags(tmp_path):
    # `s + 0` is bit-identical for ints but NOT for floats (-0.0, NaN
    # payloads): only the bare leaf is the identity carry.
    src = PC301_SRC.replace("jnp.zeros_like(s)", "s + 0")
    findings = _lint_src(tmp_path, src)
    assert [f.rule for f in findings] == ["PC301"], \
        "\n".join(f.render() for f in findings)


PC302_SRC = textwrap.dedent('''\
    import jax
    import jax.numpy as jnp

    _DEFAULT_LANES = 8


    def masked_fold(state, new):
        mask = jnp.arange(_DEFAULT_LANES) < 4
        return jax.tree.map(
            lambda s, n: jnp.where(mask, n, s),          # M-PC302
            state, new)
''')


def test_pc302_constant_derived_mask_flags(tmp_path):
    findings = _lint_src(tmp_path, PC302_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC302", _line_of(PC302_SRC, "M-PC302"))], \
        "\n".join(f.render() for f in findings)


def test_pc302_parameter_derived_mask_is_clean(tmp_path):
    src = PC302_SRC.replace(
        "def masked_fold(state, new):",
        "def masked_fold(state, new, active):",
    ).replace("mask = jnp.arange(_DEFAULT_LANES) < 4",
              "mask = active > 0")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc302_axis_index_mask_is_clean(tmp_path):
    src = PC302_SRC.replace(
        "mask = jnp.arange(_DEFAULT_LANES) < 4",
        'mask = jax.lax.axis_index("lanes") < 4')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# PC4xx: the eligibility refusal matrix. The fixture mirrors fuse()'s
# refusal set; the checker keys on the module BASENAME, so the fixture
# file is named multiquery.py.

FUSE_SRC = textwrap.dedent('''\
    class MultiQueryPlan:
        pass


    def fuse(queries):                                   # M-PC401
        for q in queries:
            if isinstance(q, MultiQueryPlan):
                raise TypeError("nested fusion is unsupported")
            if q.agg.transient:
                raise ValueError("transient sub-plans are unsupported")
            if q.agg.windowed_panes:
                raise ValueError("windowed_panes rings are unsupported")
            if not q.agg.jit_transform:
                raise ValueError("host-side transforms are unsupported")
            codec = q.codec
            if codec is not None and codec.stack_ordered:
                raise ValueError("stack_ordered codecs are unsupported")
            if q.agg.requires_codec and codec is None:
                raise ValueError("requires_codec without a codec")
        return queries
''')


def test_pc401_full_refusal_set_is_clean(tmp_path):
    findings = _lint_src(tmp_path, FUSE_SRC, name="multiquery.py")
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc401_stripped_refusal_flags_the_entry_point(tmp_path):
    src = FUSE_SRC.replace(
        '        if codec is not None and codec.stack_ordered:\n'
        '            raise ValueError("stack_ordered codecs are '
        'unsupported")\n', "")
    findings = _lint_src(tmp_path, src, name="multiquery.py")
    assert [(f.rule, f.line) for f in findings] \
        == [("PC401", _line_of(src, "M-PC401"))], \
        "\n".join(f.render() for f in findings)
    assert "stack_ordered" in findings[0].message


def test_pc401_basename_scoping(tmp_path):
    # The same stripped body under a NON-matrix basename is not an
    # entry point: the matrix binds (module, function) pairs only.
    src = FUSE_SRC.replace(
        '        if codec is not None and codec.stack_ordered:\n'
        '            raise ValueError("stack_ordered codecs are '
        'unsupported")\n', "")
    findings = _lint_src(tmp_path, src, name="helpers.py")
    assert findings == [], "\n".join(f.render() for f in findings)


def test_pc401_real_fuse_stripped_of_stack_ordered_flips(tmp_path):
    # The acceptance re-seed against the REAL entry point: renaming the
    # stack_ordered eligibility tokens out of fuse()'s guards (the
    # shape a refactor that silently drops the refusal produces) must
    # flag PC401 for exactly that matrix row.
    with open(MQ_PY) as f:
        src = f.read()
    assert "stack_ordered" in src, "fuse() eligibility moved — re-anchor"
    mutated = src.replace("stack_ordered", "stack_reordered")
    got = _lint_src(tmp_path, mutated, name="multiquery.py")
    pc401 = [f for f in got if f.rule == "PC401"]
    assert len(pc401) == 1 and "stack_ordered" in pc401[0].message, \
        "\n".join(f.render() for f in got)
    # control: the unmodified module satisfies every matrix row.
    clean = _lint_src(tmp_path, src, name="multiquery.py")
    assert [f for f in clean if f.rule.startswith("PC4")] == [], \
        "\n".join(f.render() for f in clean)


def test_pc402_renamed_entry_point_flags(tmp_path):
    src = FUSE_SRC.replace("def fuse(", "def fuse_everything(")
    findings = _lint_src(tmp_path, src, name="multiquery.py")
    assert [(f.rule, f.line) for f in findings] == [("PC402", 1)], \
        "\n".join(f.render() for f in findings)
    assert "'fuse'" in findings[0].message


def test_matrix_dirs_cover_every_matrix_module():
    # The missing-module PC402 scope map must name every matrix
    # module, or a future entry silently opts out of rename detection.
    assert set(plancheck._MATRIX_DIRS) \
        == {base for base, _fn in plancheck.REFUSAL_MATRIX}


def test_pc402_renamed_module_file_flags(tmp_path):
    # `git mv engine/multiquery.py engine/mq.py` must not silently
    # drop fuse()'s whole refusal check: a matrix module missing from
    # its linted home package flags PC402. Fixture dirs (no `engine`
    # package) stay out of scope — every other test here proves that.
    eng = tmp_path / "engine"
    eng.mkdir()
    (eng / "__init__.py").write_text("")
    (eng / "mq.py").write_text(FUSE_SRC)  # renamed: no multiquery.py
    findings = plancheck.lint_paths(
        str(tmp_path), [str(eng / "__init__.py"), str(eng / "mq.py")])
    missing = {f.message.split("'")[1] for f in findings
               if f.rule == "PC402"}
    assert "multiquery.py" in missing, \
        "\n".join(f.render() for f in findings)
    assert all(f.rule == "PC402" for f in findings)
    # restoring the canonical name clears the missing-module half
    (eng / "mq.py").rename(eng / "multiquery.py")
    findings = plancheck.lint_paths(
        str(tmp_path),
        [str(eng / "__init__.py"), str(eng / "multiquery.py")])
    assert "multiquery.py" not in {
        f.message.split("'")[1] for f in findings if f.rule == "PC402"}


# --------------------------------------------------------------------- #
# suppression scoping

def test_suppression_silences_one_rule_one_line(tmp_path):
    src = PC101_SRC.replace(
        "mode = agg.merge_mode                            # M-PC101",
        "mode = agg.merge_mode  # graphlint: disable=PC101")
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_suppression_wrong_rule_and_all(tmp_path):
    src = PC101_SRC.replace(
        "mode = agg.merge_mode                            # M-PC101",
        "mode = agg.merge_mode  # graphlint: disable=PC202")
    assert [f.rule for f in _lint_src(tmp_path, src)] == ["PC101"]
    src2 = PC101_SRC.replace(
        "mode = agg.merge_mode                            # M-PC101",
        "mode = agg.merge_mode  # graphlint: disable=all")
    assert _lint_src(tmp_path, src2) == []


def test_suppression_does_not_leak_to_other_lines(tmp_path):
    # Suppressing the PC202 call must not blot out the PC203 store two
    # lines up (per-line, per-rule scoping).
    src = textwrap.dedent('''\
        def serve(agg, mesh, chunk, store, sink):
            plan = _compiled_plan(agg, mesh)
            state, fold_chunk = plan
            store.latest_summary = state                 # M-KEEP
            sink.append(
                fold_chunk(state, chunk))  # graphlint: disable=PC202
            return state
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("PC203", _line_of(src, "M-KEEP"))], \
        "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# every seeded violation flips the CLI exit code (ISSUE 15 acceptance)

_RULE_SEEDS = {
    "PC101": {"fixture_mod.py": PC101_SRC},
    "PC102": {"fixture_mod.py": PC102_SRC},
    "PC103": {"fixture_mod.py": PC103_SRC},
    "PC201": {"fixture_mod.py": PC201_SRC},
    "PC202": {"fixture_mod.py": PC202_SRC},
    "PC203": {"fixture_mod.py": PC203_SRC},
    "PC301": {"fixture_mod.py": PC301_SRC},
    "PC302": {"fixture_mod.py": PC302_SRC},
    "PC401": {"multiquery.py": FUSE_SRC.replace(
        '        if codec is not None and codec.stack_ordered:\n'
        '            raise ValueError("stack_ordered codecs are '
        'unsupported")\n', "")},
    "PC402": {"multiquery.py": FUSE_SRC.replace(
        "def fuse(", "def fuse_everything(")},
}


@pytest.mark.parametrize("rule", sorted(_RULE_SEEDS))
def test_seeded_violation_turns_exit_nonzero(tmp_path, rule, capsys):
    for name, src in _RULE_SEEDS[rule].items():
        (tmp_path / name).write_text(src)
    rc = analysis_main(["plancheck", str(tmp_path), "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 1
    assert rule in out


def test_cli_plancheck_subcommand_exit_zero_on_tip(capsys):
    rc = analysis_main(["plancheck", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "plancheck: 0 finding(s)" in out
    assert "analysis clean (plancheck)" in out


def test_cli_list_rules_includes_pc_rules_and_src(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("PC101", "PC102", "PC103", "PC201", "PC202", "PC203",
                "PC301", "PC302", "PC401", "PC402", "SRC001"):
        assert rid in out, rid


@pytest.mark.slow  # tier-1 budget: plancheck lane; subcommand smoke stays
def test_cli_skip_plancheck(capsys):
    rc = analysis_main(["--all", "--root", REPO, "--skip-plancheck",
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(payload["tools"]) == {"abi", "jitlint", "racecheck",
                                     "contracts", "liveness"}


# --------------------------------------------------------------------- #
# analyzer robustness (satellite): a syntax error, a zero-byte file,
# and a non-UTF8 file must each produce one loud per-file SRC001 from
# EVERY covering tool — not a crash, not a silent skip.

_BROKEN_TREE = {
    "bad_syntax.py": "def broken(:\n    pass\n",
    "empty_mod.py": "",
    "not_utf8.py": b"x = '\xff\xfe'\n",
}


def test_unparseable_files_are_loud_from_every_tool(tmp_path, capsys):
    for name, src in _BROKEN_TREE.items():
        p = tmp_path / name
        p.write_bytes(src if isinstance(src, bytes) else src.encode())
    rc = analysis_main(["--all", str(tmp_path), "--root", REPO,
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    for tool in ("jitlint", "racecheck", "contracts", "plancheck",
                 "liveness"):
        fs = payload["tools"][tool]["findings"]
        assert all(f["rule"] == "SRC001" for f in fs), (tool, fs)
        names = {os.path.basename(f["path"]) for f in fs}
        assert names == set(_BROKEN_TREE), (tool, names)
    # each failure kind names its cause (one tool's stream suffices)
    msgs = " ".join(f["message"]
                    for f in payload["tools"]["plancheck"]["findings"])
    assert "syntax error" in msgs
    assert "zero-byte" in msgs
    assert "not valid UTF-8" in msgs


@pytest.mark.parametrize("tool", ["jitlint", "racecheck", "contracts",
                                  "plancheck", "liveness"])
def test_single_tool_cli_exit_nonzero_on_broken_file(tmp_path, tool,
                                                     capsys):
    (tmp_path / "bad_syntax.py").write_text("def broken(:\n")
    rc = analysis_main([tool, str(tmp_path), "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SRC001" in out


def test_syntax_error_finding_is_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, "ok = 1\ndef broken(:\n",
                         name="bad_syntax.py")
    assert [(f.rule, f.line) for f in findings] == [("SRC001", 2)]


def test_empty_init_py_is_exempt(tmp_path):
    # An empty package marker is idiomatic, not a truncation.
    findings = _lint_files(tmp_path, {"__init__.py": "",
                                      "mod.py": "x = 1\n"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_whitespace_only_module_is_not_a_truncation(tmp_path):
    # Only a literally zero-byte file is the truncation signal: a
    # whitespace/newline-only module is valid (empty) Python.
    findings = _lint_files(tmp_path, {"placeholder.py": "\n\n",
                                      "mod.py": "x = 1\n"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_nul_byte_file_is_a_finding_not_a_crash(tmp_path):
    # ast.parse rejects NUL bytes with a bare ValueError (a truncated
    # binary write): same contract — loud SRC001, never a traceback.
    findings = _lint_files(tmp_path, {"nulled.py": b"x = 1\x00\n"})
    assert [f.rule for f in findings] == ["SRC001"], \
        "\n".join(f.render() for f in findings)


def test_src001_is_deduplicated_per_tool(tmp_path, capsys):
    # One broken file, one SRC001 per tool — not one per rule pass.
    (tmp_path / "bad_syntax.py").write_text("def broken(:\n")
    rc = analysis_main(["--all", str(tmp_path), "--root", REPO,
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    for tool in ("jitlint", "racecheck", "contracts", "plancheck",
                 "liveness"):
        assert payload["tools"][tool]["count"] == 1, tool


# --------------------------------------------------------------------- #
# shared single-parse AST cache (satellite)

def test_source_cache_parses_each_file_once(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("def f():\n    return 1\n")
    cache = loader.SourceCache()
    a = cache.get(str(p))
    b = cache.get(str(p))
    assert a is b and a.tree is b.tree


def test_all_tools_share_one_parse_per_file(tmp_path, monkeypatch):
    # The satellite's contract made observable: under --all, no file is
    # ast.parse-d more than once per CLI invocation.
    (tmp_path / "mod_a.py").write_text("def f():\n    return 1\n")
    (tmp_path / "mod_b.py").write_text("def g():\n    return 2\n")
    counts = {}
    real_parse = loader.ast.parse

    def counting(src, filename="<unknown>", *args, **kwargs):
        counts[filename] = counts.get(filename, 0) + 1
        return real_parse(src, filename, *args, **kwargs)

    monkeypatch.setattr(loader.ast, "parse", counting)
    rc = analysis_main(["--all", str(tmp_path), "--root", REPO,
                        "--skip-abi"])
    assert rc == 0
    fixture_counts = {os.path.basename(f): n for f, n in counts.items()
                      if f.startswith(str(tmp_path))}
    assert fixture_counts == {"mod_a.py": 1, "mod_b.py": 1}
    assert counts and max(counts.values()) == 1, \
        {f: n for f, n in counts.items() if n > 1}


# --------------------------------------------------------------------- #
# --changed fast path (satellite)

def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=ci@test", "-c", "user.name=ci", *args],
        cwd=str(cwd), check=True, capture_output=True)


def test_changed_reports_only_changed_file_findings(tmp_path, capsys):
    _git(tmp_path, "init", "-q")
    (tmp_path / "old_mod.py").write_text(PC202_SRC)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "new_mod.py").write_text(PC203_SRC)  # untracked
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--changed", "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    names = {os.path.basename(f["path"])
             for f in payload["tools"]["plancheck"]["findings"]}
    assert names == {"new_mod.py"}


def test_changed_clean_when_everything_is_committed(tmp_path, capsys):
    # Violations exist in the tree, but nothing differs from HEAD: the
    # fast path reports nothing and exits 0 (the full lane still runs
    # the whole-package walk in CI).
    _git(tmp_path, "init", "-q")
    (tmp_path / "old_mod.py").write_text(PC202_SRC)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--changed"])
    capsys.readouterr()
    assert rc == 0


def test_changed_against_an_explicit_ref(tmp_path, capsys):
    _git(tmp_path, "init", "-q")
    (tmp_path / "old_mod.py").write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "new_mod.py").write_text(PC202_SRC)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "grow")
    assert analysis_main(["plancheck", str(tmp_path), "--root",
                          str(tmp_path), "--changed"]) == 0
    capsys.readouterr()
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--changed=HEAD~1",
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    names = {os.path.basename(f["path"])
             for f in payload["tools"]["plancheck"]["findings"]}
    assert names == {"new_mod.py"}


def test_changed_files_root_below_git_toplevel(tmp_path):
    # `git diff --name-only` prints toplevel-relative paths; with
    # --root pointing at a subdirectory of the repo, tracked changes
    # must still resolve to real absolute paths (untracked files are
    # cwd-relative and take the other join base).
    from gelly_tpu.analysis.__main__ import _changed_files

    _git(tmp_path, "init", "-q")
    sub = tmp_path / "vendor"
    sub.mkdir()
    (sub / "a.py").write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (sub / "a.py").write_text("x = 2\n")          # tracked, modified
    (sub / "b.py").write_text("y = 1\n")          # untracked
    changed = _changed_files(str(sub), "HEAD")
    assert str(sub / "a.py") in changed
    assert str(sub / "b.py") in changed


def test_changed_space_separated_ref_form(tmp_path, capsys):
    # `--changed HEAD~1` (space form) must consume the ref, not demote
    # it to a lint path and silently diff against HEAD.
    _git(tmp_path, "init", "-q")
    (tmp_path / "old_mod.py").write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "new_mod.py").write_text(PC202_SRC)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "grow")
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--changed", "HEAD~1",
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    names = {os.path.basename(f["path"])
             for f in payload["tools"]["plancheck"]["findings"]}
    assert names == {"new_mod.py"}


def test_changed_does_not_mask_unparseable_unchanged_files(tmp_path,
                                                           capsys):
    # A broken file the diff scope would exclude still flips the exit
    # code: the whole-package rules ran blind over it, so the fast
    # path must not report "clean" (SRC001 is scope-exempt).
    _git(tmp_path, "init", "-q")
    (tmp_path / "broken.py").write_text("def broken(:\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "new_mod.py").write_text("x = 1\n")
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--changed", "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"]
            for f in payload["tools"]["plancheck"]["findings"]] \
        == ["SRC001"]


def test_changed_bad_ref_is_a_loud_error(tmp_path):
    _git(tmp_path, "init", "-q")
    with pytest.raises(SystemExit):
        analysis_main(["plancheck", str(tmp_path), "--root",
                       str(tmp_path), "--changed=no-such-ref"])


# --------------------------------------------------------------------- #
# --format=github workflow annotations (satellite)

def test_github_format_emits_error_annotations(tmp_path, capsys):
    (tmp_path / "fixture_mod.py").write_text(PC202_SRC)
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--format=github"])
    out = capsys.readouterr().out
    assert rc == 1
    ann = [ln for ln in out.splitlines() if ln.startswith("::error ")]
    assert len(ann) == 1
    line = _line_of(PC202_SRC, "M-PC202")
    assert ann[0].startswith(
        f"::error file=fixture_mod.py,line={line},title=PC202::")
    assert "hint:" in ann[0]


def test_github_format_escapes_workflow_command_data(tmp_path, capsys):
    # %, CR and LF in the message/hint must be %-escaped or GitHub
    # truncates the annotation at the first newline.
    (tmp_path / "fixture_mod.py").write_text(PC202_SRC)
    rc = analysis_main(["plancheck", str(tmp_path), "--root",
                        str(tmp_path), "--format=github"])
    out = capsys.readouterr().out
    assert rc == 1
    for ln in out.splitlines():
        if ln.startswith("::error "):
            assert "\r" not in ln and len(ln.splitlines()) == 1


def test_github_annotation_escapes_property_delimiters():
    # ',' and ':' in property values are workflow-command delimiters
    # and must be %-escaped or GitHub mis-parses the annotation.
    from gelly_tpu.analysis import Finding
    from gelly_tpu.analysis.__main__ import _github_annotation

    f = Finding("/r/a,b/mod.py", 3, "PC202", "msg: 100% broken",
                hint="h")
    ann = _github_annotation(f, "/r")
    assert ann.startswith("::error file=a%2Cb/mod.py,line=3,"
                          "title=PC202::")
    assert ann.endswith("msg: 100%25 broken | hint: h")


def test_github_format_clean_tip_emits_no_annotations(capsys):
    rc = analysis_main(["plancheck", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO, "--format=github"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "::error" not in out
    assert "plancheck: 0 finding(s)" in out
