"""Subprocess body for the fused multi-query kill -9 crash test
(test_multiquery.py).

Runs the FULL pipelined engine path over a fused 3-query plan (CC +
degrees + spanner-with-its-own-merge-window), checkpointing the fused
state — every query's leaves plus the fold-step counter in ONE file at
ONE position — and throttled so the kill lands with units in flight.
The second incarnation resumes and must produce per-query emissions
bit-identical to an uninterrupted run: the single recorded position
covers every query at once, and the restored step counter replays the
masked per-query merge windows at exactly the chunks the golden run
merged at.

argv: <checkpoint_path> <out_npz> [emit_sleep_seconds]
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gelly_tpu import edge_stream_from_edges  # noqa: E402
from gelly_tpu.engine.aggregation import run_aggregation  # noqa: E402
from gelly_tpu.engine.checkpoint import save_checkpoint  # noqa: E402
from gelly_tpu.library.connected_components import cc_query  # noqa: E402
from gelly_tpu.library.degrees import degrees_query  # noqa: E402
from gelly_tpu.library.spanner import spanner_query  # noqa: E402

N_EDGES = int(os.environ.get("GELLY_MQ_EDGES", "1024"))
N_V = int(os.environ.get("GELLY_MQ_NV", "96"))
CHUNK = int(os.environ.get("GELLY_MQ_CHUNK", "32"))
# GELLY_MQ_COMPRESSED=1 runs the fused-CODEC plan instead (the shared
# compress stage + fold_compressed path): the kill must land with
# compressed payload units in flight and resume bit-identically too.
COMPRESSED = os.environ.get("GELLY_MQ_COMPRESSED", "0") == "1"


def build_stream():
    rng = np.random.default_rng(29)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    return edge_stream_from_edges(
        [(int(a), int(b)) for a, b in pairs],
        vertex_capacity=N_V, chunk_size=CHUNK,
    )


def build_queries():
    if COMPRESSED:
        from gelly_tpu.library.bipartiteness import bipartiteness_query

        # The fused-codec set is all-accumulating by construction (the
        # shared compress stage's eligibility rule); the step counter
        # still rides the checkpoint and must resume exactly.
        return [
            cc_query(N_V, compressed=True, codec="sparse"),
            degrees_query(N_V, compressed=True, codec="sparse"),
            bipartiteness_query(N_V, compressed=True, codec="sparse"),
        ]
    return [
        cc_query(N_V),
        degrees_query(N_V),
        # The non-accumulating query: its merge window (every=2) rides
        # the checkpointed step counter — a resume that restarted the
        # counter would merge at the wrong chunks and diverge.
        spanner_query(N_V, k=2, every=2),
    ]


def main(argv):
    ckpt_path, out_path = argv[0], argv[1]
    sleep_s = float(argv[2]) if len(argv) > 2 else 0.0
    res = run_aggregation(
        None, build_stream(), queries=build_queries(),
        merge_every=2, fold_batch=2,
        checkpoint_path=ckpt_path, checkpoint_every=1,
        resume=os.path.exists(ckpt_path),
        codec_workers=2, h2d_depth=2,
    )
    final = None
    for final in res:
        if sleep_s:
            # Throttled consumer: the staging/H2D legs run ahead, so the
            # parent's SIGKILL lands with units in flight.
            time.sleep(sleep_s)
    import jax

    host = jax.tree.map(np.asarray, final)
    save_checkpoint(out_path, host, position=res.stats["chunks"])


if __name__ == "__main__":
    main(sys.argv[1:])
