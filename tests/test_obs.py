"""Observability runtime: event bus, span tracer, Chrome-trace export.

The acceptance bar (ISSUE 5): an exported trace from a reduced capture is
VALID Chrome-trace JSON with >= 1 span per pipeline stage per unit and
worker/slot attribution; every injected fault of a seeded FaultPlan shows
as an instant event; runtime behavior (retries, faults, windows) is
assertable off the event bus, not log text.
"""

import json

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges, obs
from gelly_tpu.engine import faults
from gelly_tpu.library.connected_components import (
    connected_components,
    labels_to_components,
)

EDGES = [(1, 2), (2, 3), (4, 5), (1, 3), (5, 6), (7, 8), (2, 4), (6, 9)]
EXPECTED = [[1, 2, 3, 4, 5, 6, 9], [7, 8]]


def _run_cc(tracer=None, chunk_size=2, merge_every=2, **agg_kw):
    s = edge_stream_from_edges(EDGES, vertex_capacity=32,
                               chunk_size=chunk_size)
    agg = connected_components(32)
    if tracer is None:
        labels = s.aggregate(agg, merge_every=merge_every, **agg_kw).result()
    else:
        with obs.install(tracer):
            labels = s.aggregate(agg, merge_every=merge_every,
                                 **agg_kw).result()
    assert labels_to_components(labels, s.ctx) == EXPECTED
    return labels


# --------------------------------------------------------------------- #
# event bus


def test_bus_counters_gauges_and_snapshot():
    bus = obs.EventBus()
    bus.inc("a.count")
    bus.inc("a.count", 2.5)
    bus.gauge("a.depth", 7)
    snap = bus.snapshot()
    assert snap["counters"]["a.count"] == 3.5
    assert snap["gauges"]["a.depth"] == 7
    # snapshot is a copy, not a view
    bus.inc("a.count")
    assert snap["counters"]["a.count"] == 3.5


def test_bus_emit_counts_notifies_and_traces():
    bus = obs.EventBus()
    seen = []
    unsub = bus.subscribe(lambda name, fields: seen.append((name, fields)))
    tr = obs.SpanTracer()
    with obs.install(tr):
        bus.emit("x.fired", boundary="h2d", index=3)
    unsub()
    bus.emit("x.fired", boundary="h2d", index=4)  # after unsubscribe
    assert bus.snapshot()["counters"]["x.fired"] == 2
    assert seen == [("x.fired", {"boundary": "h2d", "index": 3})]
    inst = tr.instants("x.fired")
    assert len(inst) == 1 and inst[0]["args"]["index"] == 3


def test_bus_scope_isolates_and_restores():
    outer = obs.get_bus()
    outer_count = outer.snapshot()["counters"].get("scoped.c", 0)
    with obs.scope() as inner:
        assert obs.get_bus() is inner
        obs.get_bus().inc("scoped.c")
        assert inner.snapshot()["counters"]["scoped.c"] == 1
    assert obs.get_bus() is outer
    assert outer.snapshot()["counters"].get("scoped.c", 0) == outer_count


# --------------------------------------------------------------------- #
# span tracer


def test_tracer_ring_is_bounded_and_counts_drops():
    tr = obs.SpanTracer(capacity=4)
    for i in range(10):
        tr.instant("e", i=i)
    recs = tr.records()
    assert len(recs) == 4
    assert [r["args"]["i"] for r in recs] == [6, 7, 8, 9]  # newest kept
    assert tr.dropped == 6


def test_tracer_span_interval_and_attribution():
    tr = obs.SpanTracer()
    t0 = tr.now()
    tr.span("compress", "compress/w1", t0, unit=5, edges=100)
    (sp,) = tr.spans("compress")
    assert sp["dur"] >= 0 and sp["ts"] == t0
    assert sp["args"] == {"unit": 5, "edges": 100}
    assert sp["track"] == "compress/w1"
    assert isinstance(sp["tid"], int) and sp["thread"]


def test_tracer_install_does_not_nest():
    t1, t2 = obs.SpanTracer(), obs.SpanTracer()
    assert obs.active_tracer() is None  # disabled is the default state
    with obs.install(t1):
        assert obs.active_tracer() is t1
        with pytest.raises(RuntimeError, match="already installed"):
            with obs.install(t2):
                pass
    assert obs.active_tracer() is None


# --------------------------------------------------------------------- #
# chrome trace export


def test_chrome_export_golden_shape(tmp_path):
    tr = obs.SpanTracer()
    bus = obs.EventBus()
    bus.inc("engine.units_folded", 3)
    t0 = tr.now()
    tr.span("fold", "fold", t0, unit=0)
    tr.instant("window_close", window=1)
    trace = obs.write_chrome_trace(str(tmp_path / "t.json"), tr, bus=bus,
                                   extra={"capture": "test"})
    on_disk = json.loads((tmp_path / "t.json").read_text())
    assert on_disk == trace
    assert on_disk["displayTimeUnit"] == "ms"
    assert on_disk["otherData"]["trace_id"] == tr.trace_id
    assert on_disk["otherData"]["capture"] == "test"
    assert on_disk["otherData"]["counters"]["engine.units_folded"] == 3
    phases = {e["ph"] for e in on_disk["traceEvents"]}
    assert phases == {"M", "X", "i"}
    # one named track per distinct track string + process_name
    names = [e for e in on_disk["traceEvents"] if e["ph"] == "M"]
    assert {e["args"]["name"] for e in names} >= {"fold", "events"}


def test_chrome_validate_rejects_malformed():
    ok = {"traceEvents": [], "displayTimeUnit": "ms", "otherData": {}}
    obs.validate_chrome_trace(ok)
    with pytest.raises(ValueError, match="traceEvents"):
        obs.validate_chrome_trace({"otherData": {}})
    with pytest.raises(ValueError, match="lacks required key"):
        obs.validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    with pytest.raises(ValueError, match="dur"):
        obs.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "X", "pid": 1, "tid": 0, "ts": 0.0},
        ]})
    with pytest.raises(ValueError, match="thread_name"):
        obs.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "X", "pid": 1, "tid": 9, "ts": 0.0,
             "dur": 1.0},
        ]})
    with pytest.raises(ValueError, match="serializable"):
        obs.validate_chrome_trace({"traceEvents": [], "otherData": {
            "bad": object()}})


# --------------------------------------------------------------------- #
# pipelined-executor integration (the tentpole acceptance)


def test_pipeline_spans_per_unit_with_attribution(tmp_path):
    tr = obs.SpanTracer(heartbeat_every_s=None)
    with obs.scope() as bus:
        _run_cc(tracer=tr, chunk_size=2, merge_every=2)
        trace = obs.write_chrome_trace(str(tmp_path / "cc.json"), tr,
                                       bus=bus)
    # 8 edges / chunk_size 2 -> 4 units (fold_batch=1). EVERY pipeline
    # stage recorded >= 1 span PER UNIT, each carrying the unit id.
    n_units = 4
    for stage in ("produce", "compress", "h2d", "fold"):
        spans = tr.spans(stage)
        units = {sp["args"]["unit"] for sp in spans}
        assert units == set(range(n_units)), (stage, units)
    # worker/slot attribution: compress tracks name their pool worker,
    # h2d tracks their double-buffer slot.
    assert all(sp["track"].startswith("compress/")
               for sp in tr.spans("compress"))
    assert all(sp["track"].startswith("h2d/slot")
               for sp in tr.spans("h2d"))
    slots = {sp["args"]["slot"] for sp in tr.spans("h2d")}
    assert slots <= {0, 1}  # default h2d_depth=2 rotation
    # compress spans carry payload/edge sizes and queue depth
    for sp in tr.spans("compress"):
        assert sp["args"]["payload_bytes"] > 0
        assert sp["args"]["edges"] >= 0
        assert "queue_depth" in sp["args"]
    # window closes: 4 units / merge_every=2 -> 2 closes, as instants
    # AND merge_emit spans.
    assert len(tr.instants("window_close")) == 2
    assert len(tr.spans("merge_emit")) == 2
    # the export validated (write_chrome_trace validates) and carries
    # the shared trace id
    assert trace["otherData"]["trace_id"] == tr.trace_id
    # bus counters observed the run
    counters = bus.snapshot()["counters"]
    assert counters["engine.units_folded"] == n_units
    assert counters["engine.chunks_folded"] == 8 / 2
    assert counters["engine.edges_folded"] == len(EDGES)
    assert counters["engine.windows_closed"] == 2


def test_disabled_tracer_default_and_counters_still_flow():
    # No tracer installed: active_tracer() is None (the zero-allocation
    # guard every engine site checks) — and the always-on counters still
    # land on the bus.
    assert obs.active_tracer() is None
    with obs.scope() as bus:
        _run_cc(tracer=None)
        counters = bus.snapshot()["counters"]
        assert counters["engine.units_folded"] == 4
        assert "engine.edges_folded" not in counters  # tracer-only currency
        gauges = bus.snapshot()["gauges"]
        assert "stage.fold_dispatch.busy_s" in gauges  # timer published


def test_checkpoint_spans_and_bytes(tmp_path):
    tr = obs.SpanTracer(heartbeat_every_s=None)
    s = edge_stream_from_edges(EDGES, vertex_capacity=32, chunk_size=2)
    agg = connected_components(32)
    ck = str(tmp_path / "ck.npz")
    with obs.scope() as bus:
        with obs.install(tr):
            s.aggregate(agg, merge_every=2, checkpoint_path=ck).result()
        counters = bus.snapshot()["counters"]
    spans = tr.spans("checkpoint")
    assert spans, "checkpoint stage recorded no spans"
    assert all(sp["args"]["bytes"] > 0 for sp in spans)
    assert counters["engine.checkpoints"] == len(spans)
    assert counters["engine.checkpoint_bytes"] >= sum(
        sp["args"]["bytes"] for sp in spans) > 0


def test_heartbeat_rate_limits_and_records():
    clock = [0.0]
    hb = obs.Heartbeat(every_s=10.0, clock=lambda: clock[0])
    assert not hb.tick(position=1)  # within the interval
    clock[0] = 10.5
    tr = obs.SpanTracer()
    with obs.install(tr):
        assert hb.tick(position=2, eps=123.0)
    clock[0] = 11.0
    assert not hb.tick(position=3)
    assert hb.beats == 1
    (line,) = list(hb.lines)
    assert line["position"] == 2 and line["eps"] == 123.0
    (inst,) = tr.instants("heartbeat")
    assert inst["args"]["position"] == 2


def test_heartbeat_emitted_from_pipeline():
    tr = obs.SpanTracer(heartbeat_every_s=0.0)  # beat on every retired unit
    with obs.scope():
        _run_cc(tracer=tr)
    beats = tr.instants("heartbeat")
    assert beats, "no heartbeat instants on an every-unit cadence"
    last = beats[-1]["args"]
    assert last["position"] == 4          # last-retired CHUNK position
    assert "eps" in last and "staged_depth" in last and "h2d_depth" in last


# --------------------------------------------------------------------- #
# fault-injection visibility


@pytest.mark.faults
def test_every_injected_fault_is_an_instant_event():
    from gelly_tpu.engine.resilience import (
        ResilienceConfig,
        ResilientRunner,
        RetryPolicy,
    )

    def step(s, c):
        return s + np.int64(c), None

    plan = faults.FaultPlan([
        faults.Fault("step", at=1, count=2),
        faults.Fault("h2d", at=3, count=1),
    ])
    tr = obs.SpanTracer()
    with obs.scope() as bus:
        with obs.install(tr), faults.install(plan):
            runner = ResilientRunner(
                step, list(range(10)), np.int64(0),
                stage=lambda c: c,
                config=ResilienceConfig(
                    retry=RetryPolicy(max_attempts=4, base_delay=0.001,
                                      max_delay=0.01),
                    watchdog_timeout=None,
                ),
            )
            assert int(runner.run()) == sum(range(10))
        counters = bus.snapshot()["counters"]
    assert len(plan.fired) == 3
    instants = tr.instants("faults.injected")
    assert len(instants) == len(plan.fired)
    assert ([(i["args"]["boundary"], i["args"]["index"]) for i in instants]
            == [(b, idx) for b, idx, _k in plan.fired])
    assert counters["faults.injected"] == 3
    # the retries that recovered from them are counters too, not log text
    assert counters["resilience.retries"] == 3
    retry_instants = tr.instants("resilience.retries")
    assert {i["args"]["boundary"] for i in retry_instants} == {"step", "h2d"}


@pytest.mark.faults
def test_pipeline_codec_fault_instant_in_trace():
    # A seeded fault at the engine's codec boundary: the injection is
    # visible on the trace/bus even though the pipelined executor
    # propagates it (no retry inside the pipeline).
    plan = faults.FaultPlan([faults.Fault("codec", at=1, count=1)])
    tr = obs.SpanTracer(heartbeat_every_s=None)
    with obs.scope() as bus:
        with obs.install(tr), faults.install(plan):
            s = edge_stream_from_edges(EDGES, vertex_capacity=32,
                                       chunk_size=2)
            agg = connected_components(32)
            with pytest.raises(faults.FaultInjected):
                s.aggregate(agg, merge_every=2).result()
        assert bus.snapshot()["counters"]["faults.injected"] == 1
    (inst,) = tr.instants("faults.injected")
    assert inst["args"]["boundary"] == "codec"


# --------------------------------------------------------------------- #
# sharded-state gauges


def test_sharded_cc_dirty_row_gauges():
    from gelly_tpu.parallel.sharded_cc import ShardedCC

    with obs.scope() as bus:
        cc = ShardedCC(64)
        cc.fold(np.array([1, 2, 3]), np.array([2, 3, 4]))
        labels = cc.labels()
        snap = bus.snapshot()
    assert labels[1] == labels[4] == 1
    assert snap["gauges"]["sharded_cc.window_dirty_rows"] >= 4
    assert snap["gauges"]["sharded_cc.window_dirty_max_shard"] >= 1
    assert snap["counters"]["sharded_cc.dirty_rows_gathered"] >= 4
    assert (snap["counters"].get("sharded_cc.emissions_dense", 0)
            + snap["counters"].get("sharded_cc.emissions_sparse", 0)) == 1


# --------------------------------------------------------------------- #
# overhead smoke (the strict <2% contract is measured on the real
# streaming_cc_large capture by bench.py's obs block; CI machines are
# too noisy for 2% — this smoke asserts the plumbing costs little and
# the results stay bit-identical)


@pytest.mark.slow  # CI's obs lane runs it (no marker filter there);
# the strict <2% contract is the bench obs block's, on TPU captures.
def test_tracer_overhead_smoke():
    import time

    rng = np.random.default_rng(3)
    n_e, n_v = 60_000, 1 << 12
    edges = list(zip(rng.integers(0, n_v, n_e).tolist(),
                     rng.integers(0, n_v, n_e).tolist()))

    def run(tracer):
        s = edge_stream_from_edges(edges, vertex_capacity=n_v,
                                   chunk_size=8192)
        agg = connected_components(n_v)
        t0 = time.perf_counter()
        if tracer is None:
            labels = s.aggregate(agg, merge_every=4).result()
        else:
            with obs.install(tracer):
                labels = s.aggregate(agg, merge_every=4).result()
        return np.asarray(labels), time.perf_counter() - t0

    # Warm compile, then best-of-3 each way.
    run(None)
    off = min(run(None)[1] for _ in range(3))
    with obs.scope():
        l_off = run(None)[0]
        best_on, l_on = float("inf"), None
        for _ in range(3):
            tr = obs.SpanTracer(heartbeat_every_s=None)
            l_on, dt = run(tr)
            best_on = min(best_on, dt)
    assert np.array_equal(l_off, l_on)  # tracing never changes results
    overhead = best_on / off - 1.0
    assert overhead < 0.5, f"tracer overhead {overhead:.1%} on smoke run"


@pytest.mark.racecheck
def test_heartbeat_concurrent_ticks_stamp_unique_beat_numbers():
    """Regression (racecheck RC001 class): the beat line used to read
    self.beats AFTER releasing the lock, so two threads that both won a
    beat could stamp the same number. Beats must be attributable 1:1."""
    import threading

    from gelly_tpu.obs.heartbeat import Heartbeat

    hb = Heartbeat(every_s=0, max_lines=4096)
    n_threads, per_thread = 8, 50

    def hammer():
        for _ in range(per_thread):
            assert hb.tick(src=threading.get_ident())

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert hb.beats == total
    beat_nos = [line["beat"] for line in hb.lines]
    assert len(beat_nos) == total
    assert sorted(beat_nos) == list(range(1, total + 1))


# --------------------------------------------------------------------- #
# streaming histograms (ISSUE 14: fixed-memory log-bucketed latency
# distributions on the bus, zero-cost when disabled)


def test_histogram_quantiles_and_extrema():
    h = obs.StreamingHistogram()
    for v in range(1, 101):  # 1..100 ms
        h.record(float(v))
    s = h.snapshot()
    assert s["count"] == 100
    assert s["min"] == 1.0 and s["max"] == 100.0
    # Log-bucket estimate: within one bucket (<= ~9% relative error).
    assert 45.0 <= s["p50"] <= 60.0
    assert 85.0 <= s["p90"] <= 100.0
    assert s["p99"] <= 100.0  # clamped at the exact max
    assert s["p50"] <= s["p90"] <= s["p99"]


def test_histogram_merge_and_edge_values():
    a, b = obs.StreamingHistogram(), obs.StreamingHistogram()
    a.record(1.0)
    a.record(2.0)
    b.record(1000.0)
    b.record(-5.0)   # clamps into the lowest bucket, never raises
    b.record(float("nan"))
    a.merge(b)
    s = a.snapshot()
    assert s["count"] == 5
    assert s["max"] == 1000.0
    assert a.quantile(1.0) == 1000.0
    e = obs.StreamingHistogram()
    assert e.quantile(0.5) == 0.0 and e.snapshot()["count"] == 0
    with pytest.raises(ValueError, match="q must be"):
        e.quantile(1.5)


def test_histogram_single_sample_reports_its_value():
    h = obs.StreamingHistogram()
    h.record(3.7)
    s = h.snapshot()
    assert s["p50"] == s["p99"] == 3.7  # clamped to exact extrema


@pytest.mark.racecheck
def test_histogram_concurrent_records_lose_nothing():
    import threading

    h = obs.StreamingHistogram()
    n_threads, per_thread = 8, 500

    def hammer(i):
        for j in range(per_thread):
            h.record(float(i * per_thread + j + 1))

    ts = [threading.Thread(target=hammer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.snapshot()["count"] == n_threads * per_thread


def test_bus_observe_snapshot_and_scope_isolation():
    with obs.scope() as bus:
        bus.observe("engine.fold_dispatch_ms", 2.0)
        bus.observe("engine.fold_dispatch_ms", 4.0)
        snap = bus.snapshot()
        assert snap["histograms"]["engine.fold_dispatch_ms"]["count"] == 2
        assert bus.quantile("engine.fold_dispatch_ms", 1.0) == 4.0
        assert bus.quantile("missing", 0.5, default=-1.0) == -1.0
    # scope isolation: the outer bus never saw the histogram
    assert "engine.fold_dispatch_ms" not in obs.get_bus().snapshot()[
        "histograms"]


def test_recording_flag_scoped_and_forced():
    assert not obs.recording()
    with obs.record_metrics():
        assert obs.recording()
        with obs.record_metrics():
            assert obs.recording()
        assert obs.recording()
    assert not obs.recording()
    obs.set_recording(True)
    try:
        assert obs.recording()
    finally:
        obs.set_recording(False)
    assert not obs.recording()


def test_histograms_and_watermarks_zero_work_when_disabled():
    # Neither a tracer nor recording: the run must not create a single
    # histogram or watermark entry (the zero-cost contract's observable
    # half; the guard itself is `telemetry`-bound once per run).
    assert obs.active_tracer() is None and not obs.recording()
    with obs.scope() as bus:
        _run_cc(tracer=None)
        snap = bus.snapshot()
    assert snap["histograms"] == {}
    assert snap["watermarks"] == {}


def test_recording_without_tracer_populates_histograms_and_watermarks(
        tmp_path):
    ck = str(tmp_path / "ck.npz")
    with obs.scope() as bus, obs.record_metrics():
        s = edge_stream_from_edges(EDGES, vertex_capacity=32, chunk_size=2)
        agg = connected_components(32)
        s.aggregate(agg, merge_every=2, checkpoint_path=ck).result()
        snap = bus.snapshot()
    hists = snap["histograms"]
    # The hot boundaries: fold dispatch, merge close, checkpoint write,
    # plus the e2e ingress→fold/durable pair.
    for name in ("engine.fold_dispatch_ms", "engine.merge_emit_ms",
                 "engine.checkpoint_write_ms",
                 "engine.e2e_ingress_to_fold_ms",
                 "engine.e2e_ingress_to_durable_ms"):
        assert hists[name]["count"] >= 1, name
        assert hists[name]["p99"] >= hists[name]["p50"] >= 0.0
    # 4 units folded -> 4 fold-dispatch samples
    assert hists["engine.fold_dispatch_ms"]["count"] == 4
    # End of stream: every stamp retired durable, backlog age is zero.
    assert snap["watermarks"]["stream"]["pending"] == 0
    assert snap["gauges"]["engine.backlog_age_s"] == 0.0


def test_watermarks_ledger_semantics():
    clock = [100.0]
    wm = obs.Watermarks(clock=lambda: clock[0])
    wm.seed("s", 2)
    wm.stamp("s", 1)           # below the seed base: dropped
    wm.stamp("s", 2)
    clock[0] = 101.0
    wm.stamp("s", 3)
    wm.stamp("s", 2, t=999.0)  # first stamp wins
    assert wm.oldest_position("s") == 2
    clock[0] = 104.0
    assert wm.backlog_age("s") == pytest.approx(4.0)
    assert wm.max_backlog_age() == pytest.approx(4.0)
    bus = obs.EventBus()
    wm.retire_fold("s", 3, bus=bus, prefix="engine")
    wm.retire_fold("s", 3, bus=bus, prefix="engine")  # once per position
    assert bus.snapshot()["histograms"][
        "engine.e2e_ingress_to_fold_ms"]["count"] == 1
    wm.retire_durable("s", 3, bus=bus, prefix="engine")
    assert wm.oldest_position("s") == 3
    assert bus.snapshot()["histograms"][
        "engine.e2e_ingress_to_durable_ms"]["count"] == 1
    wm.retire_durable("s", 4, bus=bus, prefix="engine")
    assert wm.backlog_age("s") == 0.0
    assert wm.snapshot()["s"]["pending"] == 0
    # unknown streams read as empty, never raise
    assert wm.backlog_age("nope") == 0.0
    assert wm.oldest_position("nope") is None
    wm.drop("s")
    assert wm.snapshot() == {}


def test_watermarks_rekey_moves_and_merges_ledgers():
    """Regression: TenantRouter.attach re-keys a started server's
    watermark stream — stamps recorded under the old key must follow
    (left behind they read as permanently growing backlog nobody
    retires)."""
    clock = [10.0]
    wm = obs.Watermarks(clock=lambda: clock[0])
    wm.stamp("stream", 0)
    wm.stamp("stream", 1)
    wm.rekey("stream", "wire:1234")
    assert wm.snapshot() == {
        "wire:1234": {"backlog_age_s": 0.0, "oldest_position": 0,
                      "pending": 2, "base": 0},
    }
    # Retirement under the NEW key reaches the moved stamps.
    wm.retire_durable("wire:1234", 2)
    assert wm.backlog_age("wire:1234") == 0.0
    assert wm.max_backlog_age() == 0.0
    # Merge semantics: first-stamp-wins into an existing ledger,
    # bases maxed, sub-base stragglers dropped.
    wm.seed("a", 2)
    wm.stamp("a", 3, t=1.0)
    wm.stamp("b", 1, t=5.0)  # below a's base: dropped by the merge
    wm.stamp("b", 3, t=9.0)  # position collision: a's stamp wins
    wm.stamp("b", 4, t=2.0)
    wm.rekey("b", "a")
    snap = wm.snapshot()["a"]
    assert snap["pending"] == 2 and snap["base"] == 2
    clock[0] = 11.0
    assert wm.backlog_age("a") == pytest.approx(10.0)  # t=1.0 survived
    # rekey of an absent stream is a no-op, never raises
    wm.rekey("ghost", "a")
    assert wm.snapshot()["a"]["pending"] == 2


def test_heartbeat_carries_serving_plane_fields():
    tr = obs.SpanTracer(heartbeat_every_s=0.0)  # beat on every unit
    with obs.scope():
        _run_cc(tracer=tr)
    beats = tr.instants("heartbeat")
    assert beats
    last = beats[-1]["args"]
    # ISSUE 14 satellite: backlog-age watermark, p99 fold dispatch,
    # staged-depth high-water since the last beat.
    assert last["backlog_age_max_s"] >= 0.0
    assert last["fold_p99_ms"] >= 0.0
    assert last["staged_hw"] >= 0


# --------------------------------------------------------------------- #
# flight recorder (rotating segments + incident-triggered dumps)


def test_tracer_segment_rotation_retains_newest_window():
    clock = [0.0]
    tr = obs.SpanTracer(segment_s=1.0, segments=3,
                        clock=lambda: clock[0])
    for i in range(10):
        clock[0] = float(i)
        tr.instant("e", i=i)
    kept = [r["args"]["i"] for r in tr.records()]
    # 3 segments x 1s: the newest 3 seconds survive; evictions counted.
    assert kept == [7, 8, 9]
    assert tr.dropped == 7
    with pytest.raises(ValueError, match="segment_s"):
        obs.SpanTracer(segment_s=0.0)
    with pytest.raises(ValueError, match="segments"):
        obs.SpanTracer(segment_s=1.0, segments=1)


def test_tracer_segment_capacity_backstop():
    clock = [0.0]
    tr = obs.SpanTracer(capacity=4, segment_s=100.0, segments=2,
                        clock=lambda: clock[0])
    for i in range(10):
        tr.instant("e", i=i)
    assert len(tr.records()) == 4  # per-segment record bound
    assert tr.dropped == 6


@pytest.mark.faults
def test_flight_recorder_dumps_on_injected_fault(tmp_path):
    plan = faults.FaultPlan([faults.Fault("codec", at=1, count=1)])
    tr = obs.SpanTracer(heartbeat_every_s=None, segment_s=10.0,
                        segments=4)
    with obs.scope() as bus:
        unsub = tr.dump_on(out_dir=str(tmp_path), bus=bus)
        with obs.install(tr), faults.install(plan):
            s = edge_stream_from_edges(EDGES, vertex_capacity=32,
                                       chunk_size=2)
            agg = connected_components(32)
            with pytest.raises(faults.FaultInjected):
                s.aggregate(agg, merge_every=2).result()
        unsub()
        counters = bus.snapshot()["counters"]
    assert len(tr.dumps) == 1
    trace = json.loads(open(tr.dumps[0]).read())
    obs.validate_chrome_trace(trace)  # the acceptance bar: valid trace
    names = {e["name"] for e in trace["traceEvents"]}
    # The spans surrounding the incident AND the incident marker itself
    # (emit() records the instant BEFORE the subscriber fan-out).
    assert "faults.injected" in names
    assert names & {"produce", "compress", "fold"}
    assert trace["otherData"]["incident"] == "faults.injected"
    assert counters["obs.flight_dumps"] == 1


def test_flight_recorder_dump_limit_and_default_events(tmp_path):
    tr = obs.SpanTracer(segment_s=10.0, segments=2)
    with obs.scope() as bus:
        unsub = tr.dump_on(out_dir=str(tmp_path), bus=bus, limit=2)
        # Default incident set: faults, watchdog timeouts, degradations.
        bus.emit("resilience.watchdog_timeouts", boundary="step")
        bus.emit("resilience.degradations", stem="x")
        bus.emit("faults.injected", boundary="h2d")  # over the limit
        bus.emit("unrelated.event")
        unsub()
        bus.emit("faults.injected", boundary="h2d")  # after unsubscribe
    assert len(tr.dumps) == 2  # limit honored; storms never fill disk
    for p in tr.dumps:
        obs.validate_chrome_trace(json.loads(open(p).read()))
    assert "watchdog" in tr.dumps[0]


def test_emit_records_instant_before_subscriber_fanout():
    tr = obs.SpanTracer()
    seen = []
    bus = obs.EventBus()
    bus.subscribe(
        lambda name, fields: seen.append(len(tr.instants(name))))
    with obs.install(tr):
        bus.emit("x.incident", k=1)
    # By the time the subscriber (a flight-recorder dump) runs, the
    # incident's own instant is already in the ring it would export.
    assert seen == [1]


def test_publish_checkpoint_histogram_gated_on_recording(tmp_path):
    import time as _t

    from gelly_tpu.obs import bus as bus_mod

    p = tmp_path / "f.bin"
    p.write_bytes(b"x" * 64)
    with obs.scope() as bus:
        bus_mod.publish_checkpoint(bus, "engine", str(p),
                                   t0=_t.perf_counter())
        assert bus.snapshot()["histograms"] == {}  # recording off
        with obs.record_metrics():
            bus_mod.publish_checkpoint(bus, "engine", str(p),
                                       t0=_t.perf_counter())
        snap = bus.snapshot()
    assert snap["histograms"]["engine.checkpoint_write_ms"]["count"] == 1
    assert snap["counters"]["engine.checkpoints"] == 2
