"""Observability runtime: event bus, span tracer, Chrome-trace export.

The acceptance bar (ISSUE 5): an exported trace from a reduced capture is
VALID Chrome-trace JSON with >= 1 span per pipeline stage per unit and
worker/slot attribution; every injected fault of a seeded FaultPlan shows
as an instant event; runtime behavior (retries, faults, windows) is
assertable off the event bus, not log text.
"""

import json

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges, obs
from gelly_tpu.engine import faults
from gelly_tpu.library.connected_components import (
    connected_components,
    labels_to_components,
)

EDGES = [(1, 2), (2, 3), (4, 5), (1, 3), (5, 6), (7, 8), (2, 4), (6, 9)]
EXPECTED = [[1, 2, 3, 4, 5, 6, 9], [7, 8]]


def _run_cc(tracer=None, chunk_size=2, merge_every=2, **agg_kw):
    s = edge_stream_from_edges(EDGES, vertex_capacity=32,
                               chunk_size=chunk_size)
    agg = connected_components(32)
    if tracer is None:
        labels = s.aggregate(agg, merge_every=merge_every, **agg_kw).result()
    else:
        with obs.install(tracer):
            labels = s.aggregate(agg, merge_every=merge_every,
                                 **agg_kw).result()
    assert labels_to_components(labels, s.ctx) == EXPECTED
    return labels


# --------------------------------------------------------------------- #
# event bus


def test_bus_counters_gauges_and_snapshot():
    bus = obs.EventBus()
    bus.inc("a.count")
    bus.inc("a.count", 2.5)
    bus.gauge("a.depth", 7)
    snap = bus.snapshot()
    assert snap["counters"]["a.count"] == 3.5
    assert snap["gauges"]["a.depth"] == 7
    # snapshot is a copy, not a view
    bus.inc("a.count")
    assert snap["counters"]["a.count"] == 3.5


def test_bus_emit_counts_notifies_and_traces():
    bus = obs.EventBus()
    seen = []
    unsub = bus.subscribe(lambda name, fields: seen.append((name, fields)))
    tr = obs.SpanTracer()
    with obs.install(tr):
        bus.emit("x.fired", boundary="h2d", index=3)
    unsub()
    bus.emit("x.fired", boundary="h2d", index=4)  # after unsubscribe
    assert bus.snapshot()["counters"]["x.fired"] == 2
    assert seen == [("x.fired", {"boundary": "h2d", "index": 3})]
    inst = tr.instants("x.fired")
    assert len(inst) == 1 and inst[0]["args"]["index"] == 3


def test_bus_scope_isolates_and_restores():
    outer = obs.get_bus()
    outer_count = outer.snapshot()["counters"].get("scoped.c", 0)
    with obs.scope() as inner:
        assert obs.get_bus() is inner
        obs.get_bus().inc("scoped.c")
        assert inner.snapshot()["counters"]["scoped.c"] == 1
    assert obs.get_bus() is outer
    assert outer.snapshot()["counters"].get("scoped.c", 0) == outer_count


# --------------------------------------------------------------------- #
# span tracer


def test_tracer_ring_is_bounded_and_counts_drops():
    tr = obs.SpanTracer(capacity=4)
    for i in range(10):
        tr.instant("e", i=i)
    recs = tr.records()
    assert len(recs) == 4
    assert [r["args"]["i"] for r in recs] == [6, 7, 8, 9]  # newest kept
    assert tr.dropped == 6


def test_tracer_span_interval_and_attribution():
    tr = obs.SpanTracer()
    t0 = tr.now()
    tr.span("compress", "compress/w1", t0, unit=5, edges=100)
    (sp,) = tr.spans("compress")
    assert sp["dur"] >= 0 and sp["ts"] == t0
    assert sp["args"] == {"unit": 5, "edges": 100}
    assert sp["track"] == "compress/w1"
    assert isinstance(sp["tid"], int) and sp["thread"]


def test_tracer_install_does_not_nest():
    t1, t2 = obs.SpanTracer(), obs.SpanTracer()
    assert obs.active_tracer() is None  # disabled is the default state
    with obs.install(t1):
        assert obs.active_tracer() is t1
        with pytest.raises(RuntimeError, match="already installed"):
            with obs.install(t2):
                pass
    assert obs.active_tracer() is None


# --------------------------------------------------------------------- #
# chrome trace export


def test_chrome_export_golden_shape(tmp_path):
    tr = obs.SpanTracer()
    bus = obs.EventBus()
    bus.inc("engine.units_folded", 3)
    t0 = tr.now()
    tr.span("fold", "fold", t0, unit=0)
    tr.instant("window_close", window=1)
    trace = obs.write_chrome_trace(str(tmp_path / "t.json"), tr, bus=bus,
                                   extra={"capture": "test"})
    on_disk = json.loads((tmp_path / "t.json").read_text())
    assert on_disk == trace
    assert on_disk["displayTimeUnit"] == "ms"
    assert on_disk["otherData"]["trace_id"] == tr.trace_id
    assert on_disk["otherData"]["capture"] == "test"
    assert on_disk["otherData"]["counters"]["engine.units_folded"] == 3
    phases = {e["ph"] for e in on_disk["traceEvents"]}
    assert phases == {"M", "X", "i"}
    # one named track per distinct track string + process_name
    names = [e for e in on_disk["traceEvents"] if e["ph"] == "M"]
    assert {e["args"]["name"] for e in names} >= {"fold", "events"}


def test_chrome_validate_rejects_malformed():
    ok = {"traceEvents": [], "displayTimeUnit": "ms", "otherData": {}}
    obs.validate_chrome_trace(ok)
    with pytest.raises(ValueError, match="traceEvents"):
        obs.validate_chrome_trace({"otherData": {}})
    with pytest.raises(ValueError, match="lacks required key"):
        obs.validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    with pytest.raises(ValueError, match="dur"):
        obs.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "X", "pid": 1, "tid": 0, "ts": 0.0},
        ]})
    with pytest.raises(ValueError, match="thread_name"):
        obs.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "X", "pid": 1, "tid": 9, "ts": 0.0,
             "dur": 1.0},
        ]})
    with pytest.raises(ValueError, match="serializable"):
        obs.validate_chrome_trace({"traceEvents": [], "otherData": {
            "bad": object()}})


# --------------------------------------------------------------------- #
# pipelined-executor integration (the tentpole acceptance)


def test_pipeline_spans_per_unit_with_attribution(tmp_path):
    tr = obs.SpanTracer(heartbeat_every_s=None)
    with obs.scope() as bus:
        _run_cc(tracer=tr, chunk_size=2, merge_every=2)
        trace = obs.write_chrome_trace(str(tmp_path / "cc.json"), tr,
                                       bus=bus)
    # 8 edges / chunk_size 2 -> 4 units (fold_batch=1). EVERY pipeline
    # stage recorded >= 1 span PER UNIT, each carrying the unit id.
    n_units = 4
    for stage in ("produce", "compress", "h2d", "fold"):
        spans = tr.spans(stage)
        units = {sp["args"]["unit"] for sp in spans}
        assert units == set(range(n_units)), (stage, units)
    # worker/slot attribution: compress tracks name their pool worker,
    # h2d tracks their double-buffer slot.
    assert all(sp["track"].startswith("compress/")
               for sp in tr.spans("compress"))
    assert all(sp["track"].startswith("h2d/slot")
               for sp in tr.spans("h2d"))
    slots = {sp["args"]["slot"] for sp in tr.spans("h2d")}
    assert slots <= {0, 1}  # default h2d_depth=2 rotation
    # compress spans carry payload/edge sizes and queue depth
    for sp in tr.spans("compress"):
        assert sp["args"]["payload_bytes"] > 0
        assert sp["args"]["edges"] >= 0
        assert "queue_depth" in sp["args"]
    # window closes: 4 units / merge_every=2 -> 2 closes, as instants
    # AND merge_emit spans.
    assert len(tr.instants("window_close")) == 2
    assert len(tr.spans("merge_emit")) == 2
    # the export validated (write_chrome_trace validates) and carries
    # the shared trace id
    assert trace["otherData"]["trace_id"] == tr.trace_id
    # bus counters observed the run
    counters = bus.snapshot()["counters"]
    assert counters["engine.units_folded"] == n_units
    assert counters["engine.chunks_folded"] == 8 / 2
    assert counters["engine.edges_folded"] == len(EDGES)
    assert counters["engine.windows_closed"] == 2


def test_disabled_tracer_default_and_counters_still_flow():
    # No tracer installed: active_tracer() is None (the zero-allocation
    # guard every engine site checks) — and the always-on counters still
    # land on the bus.
    assert obs.active_tracer() is None
    with obs.scope() as bus:
        _run_cc(tracer=None)
        counters = bus.snapshot()["counters"]
        assert counters["engine.units_folded"] == 4
        assert "engine.edges_folded" not in counters  # tracer-only currency
        gauges = bus.snapshot()["gauges"]
        assert "stage.fold_dispatch.busy_s" in gauges  # timer published


def test_checkpoint_spans_and_bytes(tmp_path):
    tr = obs.SpanTracer(heartbeat_every_s=None)
    s = edge_stream_from_edges(EDGES, vertex_capacity=32, chunk_size=2)
    agg = connected_components(32)
    ck = str(tmp_path / "ck.npz")
    with obs.scope() as bus:
        with obs.install(tr):
            s.aggregate(agg, merge_every=2, checkpoint_path=ck).result()
        counters = bus.snapshot()["counters"]
    spans = tr.spans("checkpoint")
    assert spans, "checkpoint stage recorded no spans"
    assert all(sp["args"]["bytes"] > 0 for sp in spans)
    assert counters["engine.checkpoints"] == len(spans)
    assert counters["engine.checkpoint_bytes"] >= sum(
        sp["args"]["bytes"] for sp in spans) > 0


def test_heartbeat_rate_limits_and_records():
    clock = [0.0]
    hb = obs.Heartbeat(every_s=10.0, clock=lambda: clock[0])
    assert not hb.tick(position=1)  # within the interval
    clock[0] = 10.5
    tr = obs.SpanTracer()
    with obs.install(tr):
        assert hb.tick(position=2, eps=123.0)
    clock[0] = 11.0
    assert not hb.tick(position=3)
    assert hb.beats == 1
    (line,) = list(hb.lines)
    assert line["position"] == 2 and line["eps"] == 123.0
    (inst,) = tr.instants("heartbeat")
    assert inst["args"]["position"] == 2


def test_heartbeat_emitted_from_pipeline():
    tr = obs.SpanTracer(heartbeat_every_s=0.0)  # beat on every retired unit
    with obs.scope():
        _run_cc(tracer=tr)
    beats = tr.instants("heartbeat")
    assert beats, "no heartbeat instants on an every-unit cadence"
    last = beats[-1]["args"]
    assert last["position"] == 4          # last-retired CHUNK position
    assert "eps" in last and "staged_depth" in last and "h2d_depth" in last


# --------------------------------------------------------------------- #
# fault-injection visibility


@pytest.mark.faults
def test_every_injected_fault_is_an_instant_event():
    from gelly_tpu.engine.resilience import (
        ResilienceConfig,
        ResilientRunner,
        RetryPolicy,
    )

    def step(s, c):
        return s + np.int64(c), None

    plan = faults.FaultPlan([
        faults.Fault("step", at=1, count=2),
        faults.Fault("h2d", at=3, count=1),
    ])
    tr = obs.SpanTracer()
    with obs.scope() as bus:
        with obs.install(tr), faults.install(plan):
            runner = ResilientRunner(
                step, list(range(10)), np.int64(0),
                stage=lambda c: c,
                config=ResilienceConfig(
                    retry=RetryPolicy(max_attempts=4, base_delay=0.001,
                                      max_delay=0.01),
                    watchdog_timeout=None,
                ),
            )
            assert int(runner.run()) == sum(range(10))
        counters = bus.snapshot()["counters"]
    assert len(plan.fired) == 3
    instants = tr.instants("faults.injected")
    assert len(instants) == len(plan.fired)
    assert ([(i["args"]["boundary"], i["args"]["index"]) for i in instants]
            == [(b, idx) for b, idx, _k in plan.fired])
    assert counters["faults.injected"] == 3
    # the retries that recovered from them are counters too, not log text
    assert counters["resilience.retries"] == 3
    retry_instants = tr.instants("resilience.retries")
    assert {i["args"]["boundary"] for i in retry_instants} == {"step", "h2d"}


@pytest.mark.faults
def test_pipeline_codec_fault_instant_in_trace():
    # A seeded fault at the engine's codec boundary: the injection is
    # visible on the trace/bus even though the pipelined executor
    # propagates it (no retry inside the pipeline).
    plan = faults.FaultPlan([faults.Fault("codec", at=1, count=1)])
    tr = obs.SpanTracer(heartbeat_every_s=None)
    with obs.scope() as bus:
        with obs.install(tr), faults.install(plan):
            s = edge_stream_from_edges(EDGES, vertex_capacity=32,
                                       chunk_size=2)
            agg = connected_components(32)
            with pytest.raises(faults.FaultInjected):
                s.aggregate(agg, merge_every=2).result()
        assert bus.snapshot()["counters"]["faults.injected"] == 1
    (inst,) = tr.instants("faults.injected")
    assert inst["args"]["boundary"] == "codec"


# --------------------------------------------------------------------- #
# sharded-state gauges


def test_sharded_cc_dirty_row_gauges():
    from gelly_tpu.parallel.sharded_cc import ShardedCC

    with obs.scope() as bus:
        cc = ShardedCC(64)
        cc.fold(np.array([1, 2, 3]), np.array([2, 3, 4]))
        labels = cc.labels()
        snap = bus.snapshot()
    assert labels[1] == labels[4] == 1
    assert snap["gauges"]["sharded_cc.window_dirty_rows"] >= 4
    assert snap["gauges"]["sharded_cc.window_dirty_max_shard"] >= 1
    assert snap["counters"]["sharded_cc.dirty_rows_gathered"] >= 4
    assert (snap["counters"].get("sharded_cc.emissions_dense", 0)
            + snap["counters"].get("sharded_cc.emissions_sparse", 0)) == 1


# --------------------------------------------------------------------- #
# overhead smoke (the strict <2% contract is measured on the real
# streaming_cc_large capture by bench.py's obs block; CI machines are
# too noisy for 2% — this smoke asserts the plumbing costs little and
# the results stay bit-identical)


@pytest.mark.slow  # CI's obs lane runs it (no marker filter there);
# the strict <2% contract is the bench obs block's, on TPU captures.
def test_tracer_overhead_smoke():
    import time

    rng = np.random.default_rng(3)
    n_e, n_v = 60_000, 1 << 12
    edges = list(zip(rng.integers(0, n_v, n_e).tolist(),
                     rng.integers(0, n_v, n_e).tolist()))

    def run(tracer):
        s = edge_stream_from_edges(edges, vertex_capacity=n_v,
                                   chunk_size=8192)
        agg = connected_components(n_v)
        t0 = time.perf_counter()
        if tracer is None:
            labels = s.aggregate(agg, merge_every=4).result()
        else:
            with obs.install(tracer):
                labels = s.aggregate(agg, merge_every=4).result()
        return np.asarray(labels), time.perf_counter() - t0

    # Warm compile, then best-of-3 each way.
    run(None)
    off = min(run(None)[1] for _ in range(3))
    with obs.scope():
        l_off = run(None)[0]
        best_on, l_on = float("inf"), None
        for _ in range(3):
            tr = obs.SpanTracer(heartbeat_every_s=None)
            l_on, dt = run(tr)
            best_on = min(best_on, dt)
    assert np.array_equal(l_off, l_on)  # tracing never changes results
    overhead = best_on / off - 1.0
    assert overhead < 0.5, f"tracer overhead {overhead:.1%} on smoke run"


@pytest.mark.racecheck
def test_heartbeat_concurrent_ticks_stamp_unique_beat_numbers():
    """Regression (racecheck RC001 class): the beat line used to read
    self.beats AFTER releasing the lock, so two threads that both won a
    beat could stamp the same number. Beats must be attributable 1:1."""
    import threading

    from gelly_tpu.obs.heartbeat import Heartbeat

    hb = Heartbeat(every_s=0, max_lines=4096)
    n_threads, per_thread = 8, 50

    def hammer():
        for _ in range(per_thread):
            assert hb.tick(src=threading.get_ident())

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert hb.beats == total
    beat_nos = [line["beat"] for line in hb.lines]
    assert len(beat_nos) == total
    assert sorted(beat_nos) == list(range(1, total + 1))
