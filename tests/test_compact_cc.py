"""Compact-root-space CC plan (``codec="compact"``) — the large-N device
fold with zero per-dispatch O(capacity) work (VERDICT r3 item 1).

Asserts: exact label parity vs the sparse-codec plan and the numpy oracle
(single shard and 8-virtual-device mesh), session id-assignment invariants,
rerun isolation (``on_run_start``), checkpoint/resume session rebuild
(``on_resume``), and the overflow guard.
"""

import numpy as np
import pytest

from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.library.connected_components import (
    cc_labels_numpy,
    connected_components,
)
from gelly_tpu.ops.compact_space import CompactIdSession, CompactSpaceOverflow
from gelly_tpu.parallel import mesh as mesh_lib

N_V = 512


def _rand_edges(n_e=4000, seed=0, n_v=N_V):
    rng = np.random.default_rng(seed)
    # Zipf-ish skew: exercise repeated hot vertices across chunks.
    src = rng.zipf(1.4, n_e) % n_v
    dst = rng.zipf(1.4, n_e) % n_v
    return src.astype(np.int64), dst.astype(np.int64)


def _stream(src, dst, chunk_size=256, n_v=N_V):
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, chunk_size=chunk_size,
                        table=IdentityVertexTable(n_v)),
        n_v,
    )


# --------------------------- session invariants ------------------------ #


def test_session_assign_lookup_roundtrip():
    s = CompactIdSession(64)
    ids = np.array([9, 3, 40, 7], np.int32)
    cids, new_ids, base = s.assign(ids)
    assert base == 0 and sorted(new_ids) == [3, 7, 9, 40]
    assert sorted(cids.tolist()) == [0, 1, 2, 3]
    # Re-assign with overlap: stable cids, only fresh ids get new cids.
    cids2, new2, base2 = s.assign(np.array([3, 11, 9], np.int32))
    assert base2 == 4 and new2.tolist() == [11]
    assert cids2[0] == cids[1] and cids2[2] == cids[0] and cids2[1] == 4
    assert np.array_equal(s.lookup(np.array([40, 11])), [cids[2], 4])
    with pytest.raises(KeyError):
        s.lookup(np.array([999]))


def test_session_lookup_empty_raises_keyerror():
    s = CompactIdSession(8)
    with pytest.raises(KeyError):
        s.lookup(np.array([5], np.int32))
    assert s.lookup(np.empty(0, np.int32)).shape == (0,)


def test_session_turn_ordering():
    # Concurrent stagers must take the stateful assign step in stream
    # order: a unit staged out of order blocks in await_turn until every
    # earlier unit completed (code-review r4: out-of-order assignment put
    # first-seen records in later-folded payloads, corrupting intermediate
    # emissions and checkpoint resume).
    import threading

    s = CompactIdSession(64)
    order: list[int] = []

    def worker(seq, ids):
        s.await_turn(seq)
        try:
            s.assign(np.asarray(ids, np.int32))
            order.append(seq)
        finally:
            s.complete_turn(seq)

    # Start unit 1 first; it must wait for unit 0.
    t1 = threading.Thread(target=worker, args=(1, [7, 8]))
    t1.start()
    import time

    time.sleep(0.05)
    assert order == []  # unit 1 parked
    t0 = threading.Thread(target=worker, args=(0, [7, 9]))
    t0.start()
    t0.join(5)
    t1.join(5)
    assert order == [0, 1]
    # Unit 0 assigned 7 -> cid 0: first-seen order follows stream order.
    assert np.array_equal(s.lookup(np.array([7, 9, 8])), [0, 1, 2])


def test_session_turn_wait_accounting():
    # Blocked time in await_turn accumulates into session.wait_s (the
    # engine reclassifies it out of ingest_compress busy at teardown: a
    # serial run never waits here, so booking it as compress work would
    # inflate the overlap accounting's serial-cost comparison). In-turn
    # awaits must add nothing, and reset() zeroes the accumulator.
    import threading
    import time

    s = CompactIdSession(64)
    s.await_turn(0)  # own turn: no wait booked
    s.complete_turn(0)
    assert s.wait_s == 0.0

    t2 = threading.Thread(target=lambda: (s.await_turn(2),
                                          s.complete_turn(2)))
    t2.start()
    time.sleep(0.05)  # unit 2 parks behind unit 1
    s.await_turn(1)
    s.complete_turn(1)
    t2.join(5)
    assert not t2.is_alive()
    assert s.wait_s >= 0.04  # the park was measured
    s.reset()
    assert s.wait_s == 0.0


def test_session_turn_release_before_turn_unparks_later_units():
    # A unit that fails BEFORE its turn releases out of order; the release
    # must be remembered (not discarded) so the turn counter skips the
    # dead unit once earlier units finish — otherwise later units park
    # forever (code-review r4 follow-up).
    import threading

    s = CompactIdSession(64)
    s.complete_turn(2)  # unit 2 died early, _turn still 0
    done = []

    def unit3():
        s.await_turn(3)
        done.append(3)
        s.complete_turn(3)

    t3 = threading.Thread(target=unit3)
    t3.start()
    for seq in (0, 1):
        s.await_turn(seq)
        s.complete_turn(seq)
    t3.join(5)
    assert done == [3]  # unit 3 unparked through the dead unit's slot
    assert not t3.is_alive()


def test_compact_parity_with_two_ingest_workers():
    src, dst = _rand_edges(n_e=5000, seed=29)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    res = _stream(src, dst, chunk_size=128).aggregate(
        agg, mesh=mesh_lib.make_mesh(1), merge_every=4, fold_batch=4,
        ingest_workers=2, prefetch_depth=4,
    )
    # Drain every window emission: each must equal its prefix oracle —
    # an out-of-order assignment would leave a window's new vertices
    # undecodable (-1) mid-stream (the ordered-staging guarantee).
    emitted = [np.asarray(e) for e in res]
    assert np.array_equal(emitted[-1], oracle)
    for i, lab in enumerate(emitted):
        n_pref = min((i + 1) * 4 * 128, src.shape[0])
        pref = cc_labels_numpy(
            src[:n_pref].astype(np.int32), dst[:n_pref].astype(np.int32),
            None, N_V,
        )
        assert np.array_equal(lab, pref), i


def test_session_overflow_raises():
    s = CompactIdSession(4)
    s.assign(np.array([1, 2, 3], np.int32))
    with pytest.raises(CompactSpaceOverflow):
        s.assign(np.array([10, 11], np.int32))


def test_session_rebuild_from_vertex_of():
    s = CompactIdSession(16)
    s.assign(np.array([30, 10, 20], np.int32))
    vertex_of = np.full(16, -1, np.int32)
    vertex_of[[0, 1, 2]] = [10, 20, 30]  # first-seen sorted order
    s2 = CompactIdSession(16)
    s2.rebuild_from_vertex_of(vertex_of)
    assert np.array_equal(s2.lookup(np.array([10, 20, 30])), [0, 1, 2])
    assert s2.assigned == 3
    # Holes (staged-but-unfolded cids) stay dead: next alloc skips past.
    vertex_of[5] = 50
    s2.rebuild_from_vertex_of(vertex_of)
    _, _, base = s2.assign(np.array([60], np.int32))
    assert base == 6


# ------------------------------- parity -------------------------------- #


def test_compact_label_parity_single_shard():
    src, dst = _rand_edges(seed=3)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    res = _stream(src, dst).aggregate(
        agg, mesh=mesh_lib.make_mesh(1), merge_every=4, fold_batch=2
    )
    labels = np.asarray(res.result())
    assert np.array_equal(labels, oracle)


def test_compact_matches_sparse_plan():
    src, dst = _rand_edges(seed=11)
    agg_c = connected_components(N_V, codec="compact", compact_capacity=N_V)
    agg_s = connected_components(N_V, codec="sparse")
    m1 = mesh_lib.make_mesh(1)
    lab_c = np.asarray(
        _stream(src, dst).aggregate(agg_c, mesh=m1, merge_every=2).result()
    )
    lab_s = np.asarray(
        _stream(src, dst).aggregate(agg_s, mesh=m1, merge_every=2).result()
    )
    assert np.array_equal(lab_c, lab_s)


def test_compact_wire_formats_agree():
    """The segment wire (fused native unit codec, round 5) and the pairs
    wire (per-chunk combine + (v, ri) rows) must emit identical labels —
    and both must match the numpy oracle — across batched windows."""
    from gelly_tpu.library.connected_components import (
        connected_components_compact,
    )

    src, dst = _rand_edges(seed=23)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    m1 = mesh_lib.make_mesh(1)
    labs = {}
    for wire in ("segments", "pairs"):
        agg = connected_components_compact(
            N_V, compact_capacity=N_V, wire=wire
        )
        labs[wire] = np.asarray(
            _stream(src, dst).aggregate(
                agg, mesh=m1, merge_every=4, fold_batch=2
            ).result()
        )
    assert np.array_equal(labs["segments"], oracle)
    assert np.array_equal(labs["pairs"], oracle)


def test_unit_segments_root_first_invariant():
    """Wire invariant the device fold relies on: each segment's FIRST
    member is the component root (canonical min vertex), and lengths sum
    to the member count."""
    from gelly_tpu.utils import native

    if not native.unit_segments_available():
        import pytest

        pytest.skip("native unit segment codec unavailable")
    rng = np.random.default_rng(7)
    src = (rng.zipf(1.3, 20000) % 3000).astype(np.int32)
    dst = (rng.zipf(1.3, 20000) % 3000).astype(np.int32)
    m, ln = native.cc_unit_forest_segments(src, dst, None, 3000, block=997)
    assert int(ln.sum()) == m.shape[0]
    starts = np.concatenate([[0], np.cumsum(ln)[:-1]])
    seg_of = np.repeat(np.arange(ln.shape[0]), ln)
    roots = m[starts]
    # Root-first + canonical min: the root is the minimum of its segment.
    mins = np.full(ln.shape[0], np.iinfo(np.int32).max)
    np.minimum.at(mins, seg_of, m)
    assert np.array_equal(roots, mins)


def test_compact_rerun_same_agg_instance():
    # on_run_start must reset the session: a second run with the same agg
    # re-assigns ids from scratch (fresh device state needs fresh newv).
    src, dst = _rand_edges(seed=5)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    for _ in range(2):
        labels = np.asarray(
            _stream(src, dst).aggregate(
                agg, mesh=mesh_lib.make_mesh(1), merge_every=4
            ).result()
        )
        assert np.array_equal(labels, oracle)


def test_compact_mesh_parity():
    src, dst = _rand_edges(n_e=6000, seed=7)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    m = mesh_lib.make_mesh()  # all 8 virtual CPU devices
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    res = _stream(src, dst).aggregate(
        agg, mesh=m, merge_every=8, fold_batch=8
    )
    labels = np.asarray(res.result())
    assert np.array_equal(labels, oracle)


def test_compact_per_window_emissions_improve():
    # Every window emission is a valid prefix CC labeling; the final one is
    # the full-stream oracle (continuously-improving summary semantics).
    src, dst = _rand_edges(n_e=2000, seed=13)
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    emitted = [
        np.asarray(e)
        for e in _stream(src, dst, chunk_size=500).aggregate(
            agg, mesh=mesh_lib.make_mesh(1), merge_every=1
        )
    ]
    assert len(emitted) == 4
    for i, lab in enumerate(emitted):
        n_pref = min((i + 1) * 500, src.shape[0])
        pref = cc_labels_numpy(
            src[:n_pref].astype(np.int32), dst[:n_pref].astype(np.int32),
            None, N_V,
        )
        assert np.array_equal(lab, pref)


def test_compact_checkpoint_resume(tmp_path):
    src, dst = _rand_edges(n_e=3000, seed=17)
    oracle = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             None, N_V)
    ckpt = str(tmp_path / "cc_compact.npz")
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    # First run: stop after a few windows by draining only part of the
    # stream (checkpoint fires per closed window).
    m1 = mesh_lib.make_mesh(1)
    it = iter(_stream(src, dst, chunk_size=250).aggregate(
        agg, mesh=m1, merge_every=2, checkpoint_path=ckpt
    ))
    next(it)
    next(it)
    del it
    # Resume with a FRESH agg instance (fresh session): on_resume must
    # rebuild the id table from the checkpointed vertex_of.
    agg2 = connected_components(N_V, codec="compact", compact_capacity=N_V)
    res = _stream(src, dst, chunk_size=250).aggregate(
        agg2, mesh=m1, merge_every=2, checkpoint_path=ckpt, resume=True
    )
    labels = np.asarray(res.result())
    assert np.array_equal(labels, oracle)


def test_windowed_codec_cc_parity():
    # VERDICT r3 item 8: the ingest codec engages in window_ms mode —
    # chunks are masked to one window before compression, so payloads are
    # window-scoped without carrying timestamps. Per-window emissions must
    # match the raw windowed fold exactly, for the sparse AND compact
    # codecs (the compact plan previously could not run windowed at all).
    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic

    rng = np.random.default_rng(19)
    n = 1000
    src = (rng.zipf(1.4, n) % N_V).astype(np.int64)
    dst = (rng.zipf(1.4, n) % N_V).astype(np.int64)
    ts = np.sort(rng.integers(0, 400, n)).astype(np.int64)

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, timestamps=ts, chunk_size=128,
                            table=IdentityVertexTable(N_V),
                            time=TimeCharacteristic.EVENT),
            N_V,
        )

    m1 = mesh_lib.make_mesh(1)

    def run(agg):
        return [
            np.asarray(e)
            for e in stream().aggregate(agg, mesh=m1, window_ms=100)
        ]

    raw = run(connected_components(N_V, ingest_combine=False))
    assert len(raw) >= 3
    for codec in ("sparse", "compact"):
        got = run(connected_components(
            N_V, codec=codec, compact_capacity=N_V
        ))
        assert len(got) == len(raw), codec
        for i, (g, r) in enumerate(zip(got, raw)):
            assert np.array_equal(g, r), (codec, i)


def test_windowed_codec_degrees_parity():
    # Windowed degree aggregation with the codec engaged (incl. deletion
    # events: the delta codec carries ±1, so window-scoped payloads must
    # reproduce the raw windowed fold exactly).
    from gelly_tpu.core.chunk import EDGE_ADDITION, EDGE_DELETION
    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(23)
    n = 600
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    ev = np.where(rng.random(n) < 0.2, EDGE_DELETION, EDGE_ADDITION)
    ts = np.sort(rng.integers(0, 300, n)).astype(np.int64)

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, events=ev, timestamps=ts,
                            chunk_size=100,
                            table=IdentityVertexTable(N_V),
                            time=TimeCharacteristic.EVENT),
            N_V,
        )

    m1 = mesh_lib.make_mesh(1)

    def run(agg):
        return [
            np.asarray(e)
            for e in stream().aggregate(agg, mesh=m1, window_ms=100)
        ]

    raw = run(degree_aggregate(N_V, ingest_combine=False))
    for codec in ("dense", "sparse"):
        got = run(degree_aggregate(N_V, codec=codec))
        assert len(got) == len(raw) >= 2, codec
        for i, (g, r) in enumerate(zip(got, raw)):
            assert np.array_equal(g, r), (codec, i)


def test_mesh_windowed_codec_parity():
    """VERDICT r4 item 5: window_ms + codec + S>1 — the masked chunk
    splits into S host slices whose payloads ride the sharded batch axis.
    Per-window emissions on the 8-device mesh must equal the single-shard
    windowed run for the sparse AND compact codecs, and for the degree
    codec."""
    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(29)
    n = 1200
    src = (rng.zipf(1.4, n) % N_V).astype(np.int64)
    dst = (rng.zipf(1.4, n) % N_V).astype(np.int64)
    ts = np.sort(rng.integers(0, 400, n)).astype(np.int64)

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, timestamps=ts, chunk_size=128,
                            table=IdentityVertexTable(N_V),
                            time=TimeCharacteristic.EVENT),
            N_V,
        )

    m1 = mesh_lib.make_mesh(1)
    m8 = mesh_lib.make_mesh()

    def run(agg, mesh):
        return [
            np.asarray(e)
            for e in stream().aggregate(agg, mesh=mesh, window_ms=100)
        ]

    for make in (
        lambda: connected_components(N_V, codec="sparse", merge="gather"),
        lambda: connected_components(
            N_V, codec="compact", compact_capacity=N_V
        ),
        lambda: degree_aggregate(N_V, codec="sparse"),
    ):
        single = run(make(), m1)
        mesh = run(make(), m8)
        assert len(single) >= 3
        assert len(single) == len(mesh)
        for i, (a, b) in enumerate(zip(single, mesh)):
            assert np.array_equal(a, b), (make, i)


def test_compact_requires_codec_path():
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V)
    with pytest.raises(NotImplementedError):
        agg.fold(agg.init(), None)
    with pytest.raises(ValueError):
        connected_components(N_V, codec="compact", ingest_combine=False)
