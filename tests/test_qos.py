"""QoS policy plane (``gelly_tpu/engine/qos.py``) + engine wiring.

Controller-level tests drive :class:`QosController` directly with an
injectable clock (deterministic DRR / token-bucket / ladder math);
engine-level tests stub the watermark backlog signal and prove the
full degradation ladder — limit, park (lane freed, snapshots still
answering), un-park, shed — plus admission control, with results
staying bit-identical to the single-stream oracle throughout.
"""

import time

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine.qos import (
    QOS_LIMITED,
    QOS_OK,
    QOS_PARKED,
    QOS_SHED,
    AdmissionRefused,
    QosController,
    QosPolicy,
)
from gelly_tpu.engine.tenants import MultiTenantEngine
from gelly_tpu.library.connected_components import connected_components
from gelly_tpu.obs import bus as obs_bus

pytestmark = pytest.mark.tenants

N_V = 128
CHUNK = 32


def _edges(seed: int, n_edges: int = 96, n_v: int = N_V):
    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, n_v, (n_edges, 2))
    return [(int(a), int(b)) for a, b in pairs]


def _stream(seed: int, n_edges: int = 96, n_v: int = N_V,
            chunk: int = CHUNK):
    return edge_stream_from_edges(
        _edges(seed, n_edges, n_v), vertex_capacity=n_v, chunk_size=chunk,
    )


def _cc_plan(n_v: int = N_V):
    return connected_components(n_v, merge="gather", ingest_combine=False)


def _wait(pred, timeout=20.0, step=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


# --------------------------------------------------------------------- #
# policy validation


def test_policy_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="weight"):
        QosPolicy(weight=0)
    with pytest.raises(ValueError, match="rate_limit_cps"):
        QosPolicy(rate_limit_cps=-5)
    with pytest.raises(ValueError, match="backlog_budget_s"):
        QosPolicy(backlog_budget_s=0)
    with pytest.raises(ValueError, match="limit_after"):
        QosPolicy(limit_after=0)
    with pytest.raises(ValueError, match="limited_weight_factor"):
        QosPolicy(limited_weight_factor=0)
    with pytest.raises(ValueError, match="limited_weight_factor"):
        QosPolicy(limited_weight_factor=1.5)
    with pytest.raises(ValueError, match="shed_queue_depth"):
        QosPolicy(shed_queue_depth=0)
    with pytest.raises(ValueError, match="burst"):
        QosPolicy(burst=0.5)
    with pytest.raises(ValueError, match="unpark_grace_s"):
        QosPolicy(unpark_grace_s=-1)


def test_unpark_threshold_defaults_to_half_budget():
    assert QosPolicy(backlog_budget_s=2.0).unpark_threshold() == 1.0
    assert QosPolicy(backlog_budget_s=2.0,
                     unpark_below_s=0.3).unpark_threshold() == 0.3
    assert QosPolicy().unpark_threshold() is None


def test_controller_validation():
    with pytest.raises(ValueError, match="admission"):
        QosController(admission="drop")
    with pytest.raises(ValueError, match="admission_ceiling_s"):
        QosController(admission_ceiling_s=0)
    qos = QosController()
    with pytest.raises(TypeError, match="QosPolicy"):
        qos.set_policy("t", {"weight": 2})
    qos.set_policy("t", QosPolicy(weight=2))
    assert qos.policy_for("t").weight == 2
    assert qos.policy_for("unknown") is qos.default
    assert qos.state("never-seen") == QOS_OK


# --------------------------------------------------------------------- #
# deficit-round-robin fairness


def test_drr_grants_follow_weights_exactly():
    """Weights 1:2:4 over R rounds → grants R/4 : R/2 : R (the heaviest
    tenant dispatches every round; the fairness bound floor(R*w/wmax)-1
    holds for everyone)."""
    clk = [0.0]
    qos = QosController(
        per_tenant={
            "a": QosPolicy(weight=1),
            "b": QosPolicy(weight=2),
            "c": QosPolicy(weight=4),
        },
        clock=lambda: clk[0],
    )
    R = 400
    grants = {"a": 0, "b": 0, "c": 0}
    for _ in range(R):
        clk[0] += 0.01
        for tid in qos.plan_round(["a", "b", "c"]):
            grants[tid] += 1
    assert grants["c"] == R
    for tid, w in (("a", 1), ("b", 2), ("c", 4)):
        assert grants[tid] >= (R * w) // 4 - 1
        assert grants[tid] <= (R * w) // 4 + 1


def test_drr_credit_carries_but_never_banks_unbounded():
    """A light tenant's credit carries across rounds (no starvation
    below its share) but is capped at one round's surplus — an idle
    spell cannot bank a burst."""
    clk = [0.0]
    qos = QosController(
        per_tenant={"lo": QosPolicy(weight=1), "hi": QosPolicy(weight=4)},
        clock=lambda: clk[0],
    )
    # 40 rounds with both backlogged: lo granted every 4th round.
    lo = 0
    for _ in range(40):
        clk[0] += 0.01
        lo += "lo" in qos.plan_round(["lo", "hi"])
    assert lo == 10
    # 100 rounds where lo is NOT backlogged (absent from the round):
    # its credit must not accumulate meanwhile.
    for _ in range(100):
        clk[0] += 0.01
        qos.plan_round(["hi"])
    burst = sum(
        "lo" in qos.plan_round(["lo", "hi"]) for _ in range(8)
    )
    assert burst <= 3  # ~2 grants in 8 rounds at weight 1/4, plus cap slack


def test_token_bucket_caps_rate():
    """rate_limit_cps bounds grants to rate * elapsed + burst even when
    DRR credit would allow a grant every round."""
    clk = [0.0]
    qos = QosController(
        per_tenant={"t": QosPolicy(rate_limit_cps=10, burst=2)},
        clock=lambda: clk[0],
    )
    granted = 0
    for _ in range(100):  # 1 simulated second
        clk[0] += 0.01
        granted += len(qos.plan_round(["t"]))
    assert 10 <= granted <= 13  # 10 cps + 2-token burst (+1 slack)


def test_parked_and_shed_tenants_never_granted():
    clk = [0.0]
    pol = QosPolicy(backlog_budget_s=1.0, limit_after=1, park_after=1,
                    shed_queue_depth=2)
    qos = QosController(default=pol, clock=lambda: clk[0])
    ev = lambda **kw: qos.evaluate("t", **kw)  # noqa: E731
    assert ev(backlog_age_s=5, queue_depth=0,
              active_backlog_max_s=5) == "limit"
    assert ev(backlog_age_s=5, queue_depth=0,
              active_backlog_max_s=5) == "park"
    assert qos.plan_round(["t"]) == set()
    assert ev(backlog_age_s=5, queue_depth=3,
              active_backlog_max_s=5) == "shed"
    assert qos.plan_round(["t"]) == set()
    assert qos.state("t") == QOS_SHED
    # Shed is terminal: further evaluations are inert.
    assert ev(backlog_age_s=0, queue_depth=0,
              active_backlog_max_s=0) is None


# --------------------------------------------------------------------- #
# the degradation ladder


def test_ladder_limit_park_unpark_grace_and_clear():
    clk = [0.0]
    pol = QosPolicy(backlog_budget_s=1.0, limit_after=2, park_after=2,
                    unpark_below_s=0.5, unpark_grace_s=5.0)
    qos = QosController(default=pol, clock=lambda: clk[0])

    def ev(age, depth=0, amax=None):
        return qos.evaluate(
            "t", backlog_age_s=age, queue_depth=depth,
            active_backlog_max_s=age if amax is None else amax,
        )

    # OK -> LIMITED after limit_after consecutive over-budget evals.
    assert ev(2.0) is None
    assert ev(2.0) == "limit"
    assert qos.state("t") == QOS_LIMITED
    # A below-budget eval resets the streak (but 0.7 >= unpark_below_s,
    # so the limit does not clear yet).
    assert ev(0.7) is None
    assert qos.state("t") == QOS_LIMITED
    # LIMITED -> PARKED after park_after more over-budget evals.
    assert ev(2.0) is None
    assert ev(2.0) == "park"
    assert qos.state("t") == QOS_PARKED
    # Parked holds while ACTIVE pressure stays above the threshold —
    # the tenant's OWN (stale, aging) backlog is not the gate.
    assert ev(9.0, amax=2.0) is None
    # Un-park once active pressure drains; re-enter at LIMITED.
    assert ev(9.0, amax=0.1) == "unpark"
    assert qos.state("t") == QOS_LIMITED
    # Grace holiday: own backlog still over budget, no escalation.
    clk[0] += 1.0
    assert ev(9.0) is None
    assert ev(9.0) is None
    assert qos.state("t") == QOS_LIMITED
    # Holiday over: escalation resumes (park_after=2 evals to re-park).
    clk[0] += 10.0
    assert ev(9.0) is None
    assert ev(9.0) == "park"
    # Un-park again, then fully drain: LIMITED clears to OK.
    assert ev(9.0, amax=0.0) == "unpark"
    clk[0] += 10.0
    assert ev(0.1) == "clear"
    assert qos.state("t") == QOS_OK


def test_ladder_never_engages_without_budget():
    qos = QosController(default=QosPolicy())  # backlog_budget_s=None
    for _ in range(10):
        assert qos.evaluate("t", backlog_age_s=1e9, queue_depth=10,
                            active_backlog_max_s=1e9) is None
    assert qos.state("t") == QOS_OK


def test_forget_drops_ladder_state():
    pol = QosPolicy(backlog_budget_s=1.0, limit_after=1)
    qos = QosController(default=pol)
    assert qos.evaluate("t", backlog_age_s=5, queue_depth=0,
                        active_backlog_max_s=5) == "limit"
    assert qos.counts()[QOS_LIMITED] == 1
    qos.forget("t")
    assert qos.state("t") == QOS_OK
    assert qos.counts()[QOS_LIMITED] == 0


# --------------------------------------------------------------------- #
# engine integration: weighted fair share


def test_weighted_fair_share_paces_dispatch_rounds():
    """heavy (w=4) and light (w=1), 8 chunks each: heavy folds in 8
    rounds while light is paced to every 4th round, then light runs
    solo at full quantum — 14 dispatch rounds total (vs 8 unpaced),
    results bit-identical per tenant."""
    cc = _cc_plan()
    qos = QosController(per_tenant={
        "heavy": QosPolicy(weight=4), "light": QosPolicy(weight=1),
    })
    with obs_bus.scope():
        eng = MultiTenantEngine(merge_every=1, qos=qos)
        eng.add_tier("cc", cc, CHUNK)
        eng.admit("heavy", "cc", chunks=_stream(1, n_edges=256))
        eng.admit("light", "cc", chunks=_stream(2, n_edges=256))
        out = eng.drain()
    assert eng.stats["chunks"] == 16
    assert eng.stats["dispatches"] == 14
    for tid, seed in (("heavy", 1), ("light", 2)):
        want = np.asarray(
            _stream(seed, n_edges=256).aggregate(cc, merge_every=1).result()
        )
        assert out[tid].tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# engine integration: admission control


def test_admission_refused_over_ceiling():
    cc = _cc_plan()
    qos = QosController(admission_ceiling_s=1.0)
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1, qos=qos)
        eng.add_tier("cc", cc, CHUNK)
        eng._active_backlog_age = lambda: 7.5
        with pytest.raises(AdmissionRefused) as ei:
            eng.admit("t", "cc")
        assert ei.value.tenant_id == "t"
        assert ei.value.backlog_age_s == 7.5
        assert ei.value.ceiling_s == 1.0
        assert bus.counters["qos.admissions_refused"] == 1
        assert "t" not in eng.tenant_ids()
        # Pressure drains -> the same admit succeeds.
        eng._active_backlog_age = lambda: 0.1
        assert eng.admit("t", "cc") >= 0


def test_admission_queue_resumes_when_pressure_drains():
    cc = _cc_plan()
    qos = QosController(admission_ceiling_s=1.0, admission="queue",
                        eval_every_s=0.01)
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1, qos=qos, poll_s=0.01)
        eng.add_tier("cc", cc, CHUNK)
        eng._active_backlog_age = lambda: 7.5
        assert eng.admit("t", "cc", chunks=_stream(3)) == -1
        assert bus.counters["qos.admissions_queued"] == 1
        with pytest.raises(ValueError, match="already admitted or queued"):
            eng.admit("t", "cc")
        assert "t" not in eng.tenant_ids()
        eng.start()
        try:
            # Still over the ceiling: the waiter stays parked at the door.
            time.sleep(0.2)
            assert "t" not in eng.tenant_ids()
            # Pressure drains -> the retry pass admits and the tenant
            # runs to completion.
            eng._active_backlog_age = lambda: 0.1
            assert _wait(lambda: "t" in eng.tenant_ids())
            assert _wait(lambda: bus.snapshot()["counters"].get(
                "qos.admissions_resumed", 0) == 1)
            assert _wait(lambda: eng.position("t") == 3)
            want = np.asarray(
                _stream(3).aggregate(cc, merge_every=1).result()
            )
            assert _wait(
                lambda: eng.labels("t") is not None
                and eng.labels("t").tobytes() == want.tobytes()
            )
        finally:
            eng.stop()


# --------------------------------------------------------------------- #
# engine integration: park / un-park / shed end-to-end


def _park_victim(bus, eng, ages, victim="victim", other="other"):
    """Drive the stubbed backlog signal until `victim` is parked with
    its lane freed; `other` keeps the active pressure high."""
    ages[victim] = 10.0
    ages[other] = 10.0
    assert _wait(lambda: eng.qos_state(victim) == QOS_PARKED)
    assert _wait(lambda: eng._tenants[victim].lane == -1)
    assert bus.counters["qos.parked"] >= 1


def test_park_frees_lane_unpark_restores_bit_identical():
    cc = _cc_plan()
    pol = QosPolicy(backlog_budget_s=0.5, limit_after=1, park_after=1,
                    unpark_below_s=0.25, unpark_grace_s=0.0)
    qos = QosController(default=QosPolicy(), eval_every_s=0.01,
                        per_tenant={"victim": pol})
    with obs_bus.scope() as bus:
        ages = {}
        bus.watermarks.backlog_age = lambda tid: ages.get(tid, 0.0)
        eng = MultiTenantEngine(merge_every=1, qos=qos, poll_s=0.01)
        eng.add_tier("cc", cc, CHUNK)
        eng.admit("victim", "cc")
        eng.admit("other", "cc")
        vic = list(_stream(1, n_edges=256))  # 8 chunks
        oth = list(_stream(2, n_edges=256))
        eng.start()
        try:
            for ch in vic[:2]:
                eng.submit("victim", ch)
            for ch in oth[:2]:
                eng.submit("other", ch)
            assert _wait(lambda: eng.position("victim") == 2
                         and eng.position("other") == 2)
            _park_victim(bus, eng, ages)
            assert bus.counters["qos.rate_limited"] >= 1
            # Parked but queryable: the saved row still answers, at the
            # park-time position.
            assert eng.labels("victim") is not None
            assert eng.telemetry()["victim"]["qos_state"] == QOS_PARKED
            # Submitting to a parked tenant queues (no drop, no raise).
            eng.submit("victim", vic[2])
            assert eng.queue_depth("victim") == 1
            # Active pressure drains -> auto un-park onto a lane.
            ages["other"] = 0.0
            ages["victim"] = 0.0
            assert _wait(lambda: eng.qos_state("victim") != QOS_PARKED)
            assert _wait(lambda: bus.snapshot()["counters"].get(
                "qos.unparked", 0) == 1)
            assert _wait(lambda: eng._tenants["victim"].lane >= 0)
            # Feed the rest; both tenants finish bit-identical.
            for ch in vic[3:]:
                eng.submit("victim", ch)
            for ch in oth[2:]:
                eng.submit("other", ch)
            eng.finish("victim")
            eng.finish("other")
            for tid, seed in (("victim", 1), ("other", 2)):
                want = np.asarray(
                    _stream(seed, n_edges=256)
                    .aggregate(cc, merge_every=1).result()
                )
                assert _wait(
                    lambda t=tid, w=want: eng.labels(t) is not None
                    and eng.labels(t).tobytes() == w.tobytes()
                ), tid
        finally:
            eng.stop()


def test_overload_sheds_parked_tenant_and_bounds_backlog():
    """The overload contract: a parked tenant whose queue keeps growing
    past shed_queue_depth is shed — its queue is DROPPED (backlog stays
    bounded), its stream closes, and the surviving tenant completes
    bit-identically."""
    cc = _cc_plan()
    pol = QosPolicy(backlog_budget_s=0.5, limit_after=1, park_after=1,
                    unpark_below_s=0.25, shed_queue_depth=3)
    qos = QosController(default=QosPolicy(), eval_every_s=0.01,
                        per_tenant={"victim": pol})
    with obs_bus.scope() as bus:
        ages = {}
        bus.watermarks.backlog_age = lambda tid: ages.get(tid, 0.0)
        eng = MultiTenantEngine(merge_every=1, qos=qos, poll_s=0.01)
        eng.add_tier("cc", cc, CHUNK)
        eng.admit("victim", "cc")
        eng.admit("other", "cc")
        vic = list(_stream(1, n_edges=256))
        oth = list(_stream(2, n_edges=256))
        eng.start()
        try:
            for ch in vic[:2]:
                eng.submit("victim", ch)
            for ch in oth[:2]:
                eng.submit("other", ch)
            assert _wait(lambda: eng.position("victim") == 2
                         and eng.position("other") == 2)
            _park_victim(bus, eng, ages)
            # Overload the parked tenant past its shed depth.
            for ch in vic[2:8]:  # 6 queued > shed_queue_depth=3
                eng.submit("victim", ch)
            assert _wait(lambda: eng.qos_state("victim") == QOS_SHED)
            assert bus.counters["qos.shed"] == 1
            assert bus.counters["qos.chunks_dropped"] == 6
            # Bounded backlog: the dropped queue is gone, and the shed
            # stream takes no more chunks.
            assert eng.queue_depth("victim") == 0
            with pytest.raises(ValueError, match="finished"):
                eng.submit("victim", vic[2])
            # The shed tenant's folded prefix still answers.
            want_prefix = np.asarray(
                edge_stream_from_edges(
                    _edges(1, 256)[:64], vertex_capacity=N_V,
                    chunk_size=CHUNK,
                ).aggregate(cc, merge_every=1).result()
            )
            assert eng.labels("victim").tobytes() == want_prefix.tobytes()
            # The survivor completes bit-identically.
            ages["other"] = 0.0
            for ch in oth[2:]:
                eng.submit("other", ch)
            eng.finish("other")
            want = np.asarray(
                _stream(2, n_edges=256).aggregate(cc, merge_every=1).result()
            )
            assert _wait(
                lambda: eng.labels("other") is not None
                and eng.labels("other").tobytes() == want.tobytes()
            )
            assert bus.gauges.get("qos.shed_tenants") == 1
        finally:
            eng.stop()


def test_on_qos_hooks_see_every_transition():
    cc = _cc_plan()
    pol = QosPolicy(backlog_budget_s=0.5, limit_after=1, park_after=1,
                    unpark_below_s=0.25, unpark_grace_s=0.0)
    qos = QosController(default=QosPolicy(), eval_every_s=0.01,
                        per_tenant={"victim": pol})
    with obs_bus.scope() as bus:
        ages = {}
        bus.watermarks.backlog_age = lambda tid: ages.get(tid, 0.0)
        eng = MultiTenantEngine(merge_every=1, qos=qos, poll_s=0.01)
        eng.add_tier("cc", cc, CHUNK)
        seen = []
        eng.on_qos.append(lambda tid, action, info: seen.append(
            (tid, action)))
        eng.admit("victim", "cc")
        eng.admit("other", "cc")
        vic = list(_stream(1, n_edges=256))
        eng.start()
        try:
            for ch in vic[:2]:
                eng.submit("victim", ch)
            assert _wait(lambda: eng.position("victim") == 2)
            _park_victim(bus, eng, ages)
            ages["victim"] = 0.0
            ages["other"] = 0.0
            assert _wait(
                lambda: ("victim", "unpark") in seen)
            actions = [a for t, a in seen if t == "victim"]
            assert actions[:3] == ["limit", "park", "unpark"]
        finally:
            eng.stop()
