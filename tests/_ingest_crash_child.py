"""Subprocess body for the SIGKILL'd-ingest-server recovery test
(test_ingest_protocol.py) — the ``_crash_child.py`` harness pattern
applied to the wire.

Runs an :class:`~gelly_tpu.ingest.server.IngestServer` with
``auto_ack=False`` feeding a checkpointed numpy CC fold: a frame is
ACKed only after a checkpoint covering its position is durably written,
so a SIGKILL at ANY point can never double-fold an acked chunk — the
restarted incarnation resumes the sequence at its newest valid
checkpoint and the client resends exactly the unacked suffix. The fold
state carries chunk/edge counters (union is idempotent, counters are
not), so the parent's exactly-once assertion is sharp.

argv: <ckpt_dir> <port_file> <out_npz> <total_chunks> [chunk_sleep_s]
     [mode: raw|compressed] [framing: plain|stacked]

``framing=stacked`` asserts the client really coalesced (the server
counted STACKED frames) — the parent drives a ``stack=3`` client
against ``CKPT_EVERY=4``, so checkpoint positions land MID-frame and
the restart exercises the covering-frame redelivery + durable-prefix
drop seam. ``frames()`` unstacks transparently, so the fold loop and
its position assertions are IDENTICAL in both framings: that is the
point — stacking must be invisible to exactly-once.

``mode=compressed`` consumes CLIENT-COMPRESSED ``DATA_COMPRESSED``
frames instead (the parent sends sparse CC (v, root) pairs via
``send_compressed``): the pairs are union edges, so the SAME fold
applies — the child additionally asserts every staged frame really
carried the compressed flag, proving acked *compressed* chunks are
never double-folded either.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_V = 256
CKPT_EVERY = 4


def init_state() -> dict:
    return {
        "parent": np.arange(N_V, dtype=np.int32),
        "chunks": np.zeros((), dtype=np.int64),
        "edges": np.zeros((), dtype=np.int64),
    }


def _find(parent: np.ndarray, v: int) -> int:
    while parent[v] != v:
        parent[v] = parent[parent[v]]
        v = parent[v]
    return int(v)


def fold(state: dict, payload: dict) -> dict:
    parent = state["parent"].copy()
    # Raw payloads carry (src, dst) edges; compressed ones carry the
    # sparse codec's (v, root) pairs — themselves union edges, so one
    # fold serves both modes and the exactly-once counters stay sharp.
    src = np.asarray(payload["src"] if "src" in payload
                     else payload["v"])
    dst = np.asarray(payload["dst"] if "dst" in payload
                     else payload["r"])
    for a, b in zip(src.tolist(), dst.tolist()):
        ra, rb = _find(parent, a), _find(parent, b)
        if ra != rb:
            lo, hi = (ra, rb) if ra < rb else (rb, ra)
            parent[hi] = lo
    return {
        "parent": parent,
        "chunks": state["chunks"] + 1,
        "edges": state["edges"] + np.int64(src.shape[0]),
    }


def labels(state: dict) -> np.ndarray:
    parent = state["parent"].copy()
    return np.asarray([_find(parent, v) for v in range(N_V)],
                      dtype=np.int32)


def main(argv):
    ckpt_dir, port_file, out_path = argv[0], argv[1], argv[2]
    total = int(argv[3])
    sleep_s = float(argv[4]) if len(argv) > 4 else 0.0
    compressed = len(argv) > 5 and argv[5] == "compressed"
    stacked = len(argv) > 6 and argv[6] == "stacked"

    from gelly_tpu.engine.checkpoint import save_checkpoint
    from gelly_tpu.engine.resilience import CheckpointManager
    from gelly_tpu.ingest import IngestServer

    # Synchronous writes: the ack that follows a save must imply the
    # bytes are durable BEFORE the client learns about it.
    mgr = CheckpointManager(ckpt_dir, keep=3, async_write=False)
    state = init_state()
    pos = 0
    found = mgr.load_latest(like=state)
    if found is not None:
        state, pos, _meta, _path = found

    srv = IngestServer(auto_ack=False, resume_seq=pos,
                       queue_depth=8).start()
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(srv.port))
    os.replace(tmp, port_file)

    try:
        for seq, payload, is_comp in srv.frames():
            if sleep_s:
                time.sleep(sleep_s)
            assert seq == pos, f"sequence skew: frame {seq} at position {pos}"
            assert is_comp == compressed, (
                f"frame {seq}: compressed flag {is_comp} != mode "
                f"{compressed}"
            )
            state = fold(state, payload)
            pos = seq + 1
            if pos % CKPT_EVERY == 0:
                mgr.save(state, pos)
                srv.ack(pos)  # durability first, ack second
            if pos == total:
                break
        mgr.save(state, pos)
        srv.ack(pos)
        if stacked:
            # Prove the stacked path was really on the wire (a client
            # that silently degraded to per-chunk frames would make
            # this run vacuous).
            from gelly_tpu.obs import bus as obs_bus

            assert obs_bus.get_bus().counters.get(
                "ingest.frames_stacked", 0) > 0, (
                "framing=stacked but the server staged no STACKED "
                "frames"
            )
    finally:
        srv.stop()
    save_checkpoint(out_path, state, position=pos)


if __name__ == "__main__":
    main(sys.argv[1:])
