"""SLO plane + Prometheus exposition + summary-delta alert sources +
multi-host trace stitcher (ISSUE 20).

The acceptance bar: a deliberately-blown ``backlog_age_max_s`` SLO
raises its burn-rate gauge and breach event; burn rates decay over the
rolling window; per-tenant instances evaluate independently; the
Prometheus text rendering covers every bus counter/gauge/histogram;
``stitch_traces`` merges per-host rings into one validated timeline
with flow arrows at barrier boundaries.
"""

import json
import threading
import time

import pytest

from gelly_tpu.obs import bus as obs_bus
from gelly_tpu.obs import export, slo, tracing

# --------------------------------------------------------------------- #
# specs


def test_spec_builders_and_validation():
    s = slo.fold_p99_ms(25.0)
    assert (s.metric, s.quantile) == ("engine.fold_dispatch_ms", 0.99)
    s = slo.e2e_durable_p90_ms(100.0)
    assert (s.metric, s.quantile) == ("engine.e2e_ingress_to_durable_ms",
                                      0.90)
    s = slo.backlog_age_max_s(5.0)
    assert s.metric == slo.WATERMARK_BACKLOG and s.quantile is None
    s = slo.tenant_backlog_age_s(2.0)
    assert s.per_tenant and "{tenant}" in s.metric
    with pytest.raises(ValueError, match="tenant"):
        slo.SloSpec("bad", "tenants.backlog_age_s", 1.0, per_tenant=True)


# --------------------------------------------------------------------- #
# evaluation: breach / recover / burn-rate window


def test_breach_and_recover_transitions():
    with obs_bus.scope() as bus:
        events = []
        bus.subscribe(lambda n, f: events.append((n, f)))
        clk = [0.0]
        spec = slo.SloSpec("fold_p99_ms", "engine.fold_dispatch_ms",
                           10.0, quantile=0.99, window_s=60.0)
        plane = slo.SloPlane([spec], bus=bus, clock=lambda: clk[0])
        # Unpopulated histogram: absence of data is not a breach.
        assert plane.tick() == 0
        assert bus.gauges["slo.fold_p99_ms.burn_rate"] == 0.0
        bus.observe("engine.fold_dispatch_ms", 50.0)
        clk[0] = 1.0
        assert plane.tick() == 1
        assert bus.gauges["slo.breaching"] == 1
        breaches = [f for n, f in events if n == "slo.breach"]
        assert len(breaches) == 1
        assert breaches[0]["slo"] == "fold_p99_ms"
        assert breaches[0]["value"] > 10.0
        assert breaches[0]["threshold"] == 10.0
        # Breach is edge-triggered: staying in breach emits no second
        # event, but the burn rate climbs.
        clk[0] = 2.0
        assert plane.tick() == 1
        assert len([1 for n, _ in events if n == "slo.breach"]) == 1
        # Recover: a healthy p99 (new bus scope resets the histogram is
        # overkill — swap the spec threshold via a fresh plane sharing
        # state is wrong too; recover by raising the threshold spec on
        # a gauge-backed spec instead).
    with obs_bus.scope() as bus:
        events = []
        bus.subscribe(lambda n, f: events.append((n, f)))
        clk = [0.0]
        spec = slo.SloSpec("depth", "pipeline.staged_depth", 4.0,
                           window_s=60.0)
        plane = slo.SloPlane([spec], bus=bus, clock=lambda: clk[0])
        bus.gauge("pipeline.staged_depth", 9)
        assert plane.tick() == 1
        bus.gauge("pipeline.staged_depth", 1)
        clk[0] = 1.0
        assert plane.tick() == 0
        names = [n for n, _ in events]
        assert names.count("slo.breach") == 1
        assert names.count("slo.recovered") == 1
        rec = [f for n, f in events if n == "slo.recovered"][0]
        assert rec["slo"] == "depth" and rec["value"] == 1.0
        assert bus.gauges["slo.breaching"] == 0
        assert bus.gauges["slo.depth.burn_rate"] == 0.5  # 1 of 2 samples


def test_burn_rate_rolls_off_the_window():
    with obs_bus.scope() as bus:
        clk = [0.0]
        spec = slo.SloSpec("depth", "pipeline.staged_depth", 4.0,
                           window_s=10.0)
        plane = slo.SloPlane([spec], bus=bus, clock=lambda: clk[0])
        bus.gauge("pipeline.staged_depth", 9)
        plane.tick()  # t=0: breach
        bus.gauge("pipeline.staged_depth", 1)
        for t in (4.0, 8.0):
            clk[0] = t
            plane.tick()
        assert bus.gauges["slo.depth.burn_rate"] == pytest.approx(
            1 / 3, abs=1e-3)  # gauge is published rounded to 4 places
        # t=12: the t=0 breach sample ages out of the 10s window.
        clk[0] = 12.0
        plane.tick()
        assert bus.gauges["slo.depth.burn_rate"] == 0.0


def test_blown_backlog_slo_raises_burn_gauge_and_breach_event():
    """The acceptance scenario: stamp ingress with no retire, so the
    watermark ledger's backlog age climbs past a deliberately tiny
    threshold — the burn gauge and the breach event must both fire."""
    with obs_bus.scope() as bus:
        events = []
        bus.subscribe(lambda n, f: events.append((n, f)))
        plane = slo.SloPlane([slo.backlog_age_max_s(0.005)], bus=bus)
        bus.watermarks.stamp("stream", 0)
        time.sleep(0.02)  # age the un-retired chunk past 5 ms
        assert plane.tick() == 1
        assert bus.gauges["slo.backlog_age_max_s.burn_rate"] == 1.0
        assert bus.gauges["slo.breaching"] == 1
        breach = [f for n, f in events if n == "slo.breach"]
        assert breach and breach[0]["slo"] == "backlog_age_max_s"
        assert breach[0]["value"] >= 0.005


def test_per_tenant_instances_evaluate_independently():
    with obs_bus.scope() as bus:
        events = []
        bus.subscribe(lambda n, f: events.append((n, f)))
        plane = slo.SloPlane([slo.tenant_backlog_age_s(1.0)], bus=bus,
                             tenants=[3, 7])
        bus.gauge("tenants.t3.backlog_age_s", 0.2)
        bus.gauge("tenants.t7.backlog_age_s", 4.5)
        assert plane.tick() == 1
        assert bus.gauges["slo.backlog_age_s.t3.burn_rate"] == 0.0
        assert bus.gauges["slo.backlog_age_s.t7.burn_rate"] == 1.0
        breach = [f for n, f in events if n == "slo.breach"]
        assert len(breach) == 1 and breach[0]["tenant"] == 7
        assert breach[0]["key"] == "backlog_age_s.t7"
        # set_tenants reshapes the evaluated set (the tenant scheduler
        # syncs this every gauge refresh).
        plane.set_tenants([3])
        assert plane.tick() == 0


def test_plane_thread_lifecycle():
    with obs_bus.scope() as bus:
        plane = slo.SloPlane(
            [slo.SloSpec("depth", "pipeline.staged_depth", 4.0)], bus=bus)
        bus.gauge("pipeline.staged_depth", 9)
        plane.start(period_s=0.01)
        try:
            with pytest.raises(RuntimeError, match="already started"):
                plane.start(period_s=0.01)
            deadline = time.monotonic() + 5
            while ("slo.breaching" not in bus.gauges
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert bus.gauges.get("slo.breaching") == 1
        finally:
            plane.stop()
        assert plane._thread is None


# --------------------------------------------------------------------- #
# summary-delta alert sources


def test_summary_delta_watch_emits_merge_and_spike():
    with obs_bus.scope() as bus:
        events = []
        bus.subscribe(lambda n, f: events.append((n, f)))
        watch = slo.SummaryDeltaWatch(bus=bus, spike_factor=3.0,
                                      min_degree=5)
        watch.observe(components=10, max_degree=2, tenant=4, position=0)
        watch.observe(components=10, max_degree=2, tenant=4, position=1)
        assert events == []  # steady state is silent
        watch.observe(components=7, max_degree=2, tenant=4, position=2)
        watch.observe(components=7, max_degree=40, tenant=4, position=3)
        names = [n for n, _ in events]
        assert names == ["alerts.component_merge", "alerts.degree_spike"]
        merge = events[0][1]
        assert merge["components"] == 7 and merge["merged"] == 3
        assert merge["tenant"] == 4
        spike = events[1][1]
        assert spike["degree"] == 40.0 and spike["tenant"] == 4
        # Small absolute degrees never spike regardless of ratio.
        watch2 = slo.SummaryDeltaWatch(bus=bus, spike_factor=2.0,
                                       min_degree=100)
        watch2.observe(max_degree=1)
        watch2.observe(max_degree=50)
        assert [n for n, _ in events].count("alerts.degree_spike") == 1


# --------------------------------------------------------------------- #
# Prometheus exposition


def test_prometheus_text_covers_every_bus_metric():
    with obs_bus.scope() as bus:
        bus.inc("ingest.frames_sent", 3)
        bus.gauge("tenants.backlog_age_max_s", 1.25)
        bus.observe("engine.fold_dispatch_ms", 10.0)
        bus.observe("engine.fold_dispatch_ms", 30.0)
        bus.watermarks.stamp("stream", 0)
        text = slo.prometheus_text(bus)
    assert "# TYPE gelly_ingest_frames_sent_total counter" in text
    assert "gelly_ingest_frames_sent_total 3" in text
    assert "# TYPE gelly_tenants_backlog_age_max_s gauge" in text
    assert "gelly_tenants_backlog_age_max_s 1.25" in text
    assert "# TYPE gelly_engine_fold_dispatch_ms summary" in text
    assert 'gelly_engine_fold_dispatch_ms{quantile="0.99"}' in text
    assert "gelly_engine_fold_dispatch_ms_count 2" in text
    assert 'gelly_watermarks_backlog_age_s{stream="stream"}' in text
    # Text format: every non-comment line is "name[{labels}] value".
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        assert name and float(value) is not None


# --------------------------------------------------------------------- #
# multi-host trace stitcher


def _host_trace(pidx: int, shift_us: float, trace_id: str) -> dict:
    """A minimal per-host trace: one span track plus two barrier
    instants, timestamps offset by ``shift_us`` (simulating hosts whose
    monotonic clocks started at different epochs)."""
    ev = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": f"gelly_tpu:{trace_id}"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "events"}},
        {"ph": "X", "name": "fold", "cat": "gelly", "pid": 1, "tid": 1,
         "ts": 100.0 + shift_us, "dur": 10.0, "args": {"unit": pidx}},
        {"ph": "i", "name": "coordination.barrier_agreed", "cat": "gelly",
         "pid": 1, "tid": 1, "s": "g", "ts": 200.0 + shift_us,
         "args": {"epoch": 0, "position": 4, "host": pidx}},
        {"ph": "i", "name": "coordination.barrier_agreed", "cat": "gelly",
         "pid": 1, "tid": 1, "s": "g", "ts": 350.0 + shift_us,
         "args": {"epoch": 1, "position": 8, "host": pidx}},
    ]
    return {"traceEvents": ev, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id,
                          "host": {"process_index": pidx,
                                   "process_count": 2}}}


def test_stitch_traces_aligns_hosts_and_draws_flow_arrows(tmp_path):
    h0 = _host_trace(0, 0.0, "aa00")
    h1 = _host_trace(1, 123456.0, "bb11")
    p1 = tmp_path / "trace_host1.json"
    p1.write_text(json.dumps(h1))
    out = tmp_path / "trace_stitched.json"
    stitched = export.stitch_traces([h0, str(p1)], out_path=str(out))
    export.validate_chrome_trace(stitched)
    assert stitched["otherData"]["stitched_hosts"] == 2
    assert stitched["otherData"]["barrier_epochs"] == [0, 1]
    # One pid per host, both with process_name metadata.
    pids = {e["pid"] for e in stitched["traceEvents"]}
    assert pids == {1, 2}
    names = {e["pid"]: e["args"]["name"]
             for e in stitched["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names[1].startswith("host0") and names[2].startswith("host1")
    # Clock alignment: host 1's first shared barrier lands at host 0's
    # timestamp, and the relative spacing of its OWN events is kept.
    h1_barriers = [e["ts"] for e in stitched["traceEvents"]
                   if e["pid"] == 2
                   and e.get("name") == "coordination.barrier_agreed"]
    assert h1_barriers == [200.0, 350.0]
    # Flow arrows: an "s"/"f" pair per shared epoch, ids matching.
    flows = [e for e in stitched["traceEvents"] if e["ph"] in ("s", "f")]
    assert {(e["ph"], e["id"]) for e in flows} == {
        ("s", "barrier-0"), ("f", "barrier-0"),
        ("s", "barrier-1"), ("f", "barrier-1")}
    assert all(e["bp"] == "e" for e in flows if e["ph"] == "f")
    # The file written to out_path round-trips through validation.
    export.validate_chrome_trace(json.loads(out.read_text()))


def test_stitch_traces_without_shared_barriers_merges_unaligned():
    h0 = _host_trace(0, 0.0, "aa00")
    h1 = _host_trace(1, 5000.0, "bb11")
    for ev in h1["traceEvents"]:
        if ev.get("name") == "coordination.barrier_agreed":
            ev["args"]["epoch"] += 100  # disjoint epochs
    stitched = export.stitch_traces([h0, h1])
    assert stitched["otherData"]["barrier_epochs"] == []
    assert not [e for e in stitched["traceEvents"]
                if e["ph"] in ("s", "f")]
    # Unaligned: host 1 keeps its own clock.
    h1_first = [e["ts"] for e in stitched["traceEvents"]
                if e["pid"] == 2 and e["ph"] == "X"]
    assert h1_first == [5100.0]


def test_validator_rejects_malformed_flow_events():
    base = _host_trace(0, 0.0, "aa00")
    ok = dict(base, traceEvents=base["traceEvents"] + [
        {"ph": "s", "name": "barrier_flow", "cat": "gelly", "pid": 1,
         "tid": 1, "id": "x", "ts": 1.0},
        {"ph": "f", "name": "barrier_flow", "cat": "gelly", "pid": 1,
         "tid": 1, "id": "x", "ts": 2.0, "bp": "e"},
    ])
    export.validate_chrome_trace(ok)
    missing_id = dict(base, traceEvents=base["traceEvents"] + [
        {"ph": "s", "name": "f", "pid": 1, "tid": 1, "ts": 1.0}])
    with pytest.raises(ValueError, match="needs an 'id'"):
        export.validate_chrome_trace(missing_id)
    missing_bp = dict(base, traceEvents=base["traceEvents"] + [
        {"ph": "f", "name": "f", "pid": 1, "tid": 1, "id": "x",
         "ts": 1.0}])
    with pytest.raises(ValueError, match="bp"):
        export.validate_chrome_trace(missing_bp)
