"""gelly_tpu.ingest wire protocol: framing, CRC, resume, backpressure.

The edge cases the ISSUE names: a torn frame mid-write (connection dies
inside a frame), a CRC-mismatched frame (rejected + counted, expected
seq NOT advanced, client retransmits), a client reconnect resuming at
the acked sequence number, gauge-driven backpressure bounding the
staged depth at the high-water mark, and — slow-marked, in the CI
ingest lane — a SIGKILL'd server restarting without double-folding
acked chunks (the ``_crash_child.py`` harness pattern on the wire).
"""

import io
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gelly_tpu.ingest import (
    IngestClient,
    IngestServer,
    edge_payload,
    pack_frame,
    pack_payload,
    read_frame,
    unpack_payload,
)
from gelly_tpu.ingest import wire
from gelly_tpu.obs import bus as obs_bus

pytestmark = pytest.mark.ingest


# --------------------------------------------------------------------- #
# framing + payload codec


def test_frame_roundtrip():
    body = pack_payload({"v": np.arange(9, dtype=np.int32)})
    buf = io.BytesIO(pack_frame(wire.DATA, 41, body))
    ftype, seq, payload = read_frame(buf.read)
    assert (ftype, seq) == (wire.DATA, 41)
    np.testing.assert_array_equal(unpack_payload(payload)["v"],
                                  np.arange(9))


def test_payload_codec_roundtrip_and_determinism():
    p = {
        "v": np.arange(7, dtype=np.int32),
        "r": np.array([[1, 2], [3, 4]], dtype="<i8"),
        "w": np.array([0.5, 1.5], dtype="<f4"),
    }
    b1, b2 = pack_payload(p), pack_payload(dict(reversed(p.items())))
    assert b1 == b2  # sorted key order -> identical bytes/CRC
    out = unpack_payload(b1)
    assert set(out) == set(p)
    for k in p:
        np.testing.assert_array_equal(out[k], p[k])


def test_payload_codec_rejects_malformed():
    good = pack_payload({"v": np.arange(4, dtype=np.int32)})
    with pytest.raises(wire.FrameError):
        unpack_payload(good[:-3])  # shorter than its structure
    with pytest.raises(wire.FrameError):
        unpack_payload(good + b"xx")  # trailing junk


def test_header_validation():
    with pytest.raises(wire.FrameError, match="magic"):
        wire.unpack_header(b"XX" + b"\0" * (wire.HEADER_BYTES - 2))
    bad_len = struct.pack(">HBBQII", wire.MAGIC, wire.DATA, 0, 0,
                          wire.MAX_PAYLOAD + 1, 0)
    with pytest.raises(wire.FrameError, match="MAX_PAYLOAD"):
        wire.unpack_header(bad_len)
    with pytest.raises(wire.FrameError, match="frame type"):
        wire.unpack_header(struct.pack(">HBBQII", wire.MAGIC, 99, 0, 0,
                                       0, 0))


def test_crc_mismatch_detected():
    body = pack_payload({"v": np.arange(4, dtype=np.int32)})
    frame = bytearray(pack_frame(wire.DATA, 3, body))
    frame[-1] ^= 0xFF  # flip one payload byte
    with pytest.raises(wire.CrcMismatch):
        read_frame(io.BytesIO(bytes(frame)).read)
    ftype, seq, _payload, ok = wire.read_frame_checked(
        io.BytesIO(bytes(frame)).read
    )
    assert (ftype, seq, ok) == (wire.DATA, 3, False)


def test_truncated_frame_detected():
    body = pack_payload({"v": np.arange(4, dtype=np.int32)})
    frame = pack_frame(wire.DATA, 3, body)
    with pytest.raises(wire.TruncatedFrame):
        read_frame(io.BytesIO(frame[: len(frame) // 2]).read)
    # Clean EOF at a frame boundary is BYE, not an error.
    assert read_frame(io.BytesIO(b"").read)[0] == wire.BYE


# --------------------------------------------------------------------- #
# loopback server/client


def _drain(server, out, stop_after=None, delay=0.0):
    def run():
        for seq, payload in server.payloads():
            out.append((seq, payload))
            if delay:
                time.sleep(delay)
            if stop_after is not None and len(out) >= stop_after:
                return
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_loopback_stream_in_order_with_acks():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                for i in range(25):
                    cli.send(edge_payload([i], [i + 1]))
                cli.flush(timeout=10)
                assert cli.acked == 25
                assert cli.unacked_count == 0
        t.join(timeout=5)
        assert [s for s, _ in got] == list(range(25))
        assert got[7][1]["src"].tolist() == [7]
        snap = bus.snapshot()["counters"]
        assert snap["ingest.chunks_enqueued"] == 25
        assert snap["ingest.acks_sent"] >= 1


def test_reconnect_resumes_at_acked_seq():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            for i in range(10):
                cli.send(edge_payload([i], [i]))
            cli.flush(timeout=10)
            # Drop the connection without BYE; reconnect re-handshakes
            # and the stream continues at the acked position.
            cli._teardown_socket()
            cli.reconnect()
            for i in range(10, 15):
                cli.send(edge_payload([i], [i]))
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == list(range(15))
        assert bus.snapshot()["counters"]["ingest.chunks_enqueued"] == 15


def test_corrupt_frame_rejected_and_retransmitted():
    """A CRC-mismatched DATA frame bumps ``ingest.frames_rejected``,
    does NOT advance the expected seq, and the client's REJECT handler
    retransmits — the stream completes exactly-once anyway."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            cli.send(edge_payload([0], [0]))
            cli.flush(timeout=10)
            # Inject a corrupt frame for seq 1 BEHIND the client's back
            # (raw socket write with a flipped payload byte), then send
            # the real seq 1 through the client: the corrupt copy is
            # rejected, the real one lands.
            body = pack_payload(edge_payload([1], [1]))
            frame = bytearray(pack_frame(wire.DATA, 1, body))
            frame[-1] ^= 0xFF
            with cli._send_lock:
                cli._sock.sendall(bytes(frame))
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_rejected", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.next_seq == 1  # never advanced past bad bytes
            cli.send(edge_payload([1], [1]))
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        snap = bus.snapshot()["counters"]
        assert snap["ingest.frames_rejected"] >= 1
        assert [s for s, _ in got] == [0, 1]
        assert got[1][1]["src"].tolist() == [1]


def test_torn_frame_mid_write_enqueues_nothing():
    """A connection that dies mid-frame (header + partial payload)
    must stage nothing, count ``ingest.frames_truncated``, and leave
    the sequence intact for the next connection."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            body = pack_payload(edge_payload([5], [6]))
            frame = pack_frame(wire.DATA, 0, body)
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.sendall(frame[: len(frame) - 7])  # torn mid-payload
            raw.close()
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_truncated", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert bus.snapshot()["counters"]["ingest.frames_truncated"] == 1
            assert srv.next_seq == 0
            # The stream is still healthy: a proper client delivers.
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send(edge_payload([5], [6]))
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0]


def test_duplicate_frames_dropped_and_reacked():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            cli.send(edge_payload([0], [0]))
            cli.flush(timeout=10)
            # Replay seq 0 raw (a reconnect race): dropped, re-acked.
            body = pack_payload(edge_payload([0], [0]))
            with cli._send_lock:
                cli._sock.sendall(pack_frame(wire.DATA, 0, body))
            cli.send(edge_payload([1], [1]))
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1]
        assert bus.snapshot()["counters"]["ingest.frames_duplicate"] == 1


def test_backpressure_bounds_staged_depth_at_high_water():
    """The acceptance-criteria contract: with high_water H and a slow
    consumer, the ``ingest.staged_depth`` gauge never exceeds H, PAUSE
    frames reach the client, and engagements are published."""
    H = 3
    with obs_bus.scope() as bus:
        depths: list = []
        with IngestServer(queue_depth=32, high_water=H, low_water=1,
                          pause_poll_s=0.002) as srv:
            orig_gauge = bus.gauge

            def spy_gauge(name, value):
                if name == "ingest.staged_depth":
                    depths.append(value)
                orig_gauge(name, value)

            bus.gauge = spy_gauge
            got: list = []
            t = _drain(srv, got, delay=0.01)
            with IngestClient("127.0.0.1", srv.port,
                              send_pause_timeout=30) as cli:
                for i in range(40):
                    cli.send(edge_payload([i], [i]))
                cli.flush(timeout=30)
        t.join(timeout=10)
        snap = bus.snapshot()["counters"]
        assert len(got) == 40
        assert snap["ingest.backpressure_engaged"] >= 1
        assert snap["ingest.pauses_received"] >= 1
        assert depths and max(depths) <= H
        assert bus.snapshot()["gauges"]["ingest.paused"] == 0  # released


def test_backpressure_is_gauge_driven():
    """The server watches the ENGINE's ``pipeline.staged_depth`` gauge
    too: a deep engine pipeline pauses wire admission even when the
    server's own queue is empty."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=32, high_water=4, low_water=1,
                          pause_poll_s=0.002) as srv:
            got: list = []
            t = _drain(srv, got)
            bus.gauge("pipeline.staged_depth", 10)  # engine side is deep
            with IngestClient("127.0.0.1", srv.port,
                              send_pause_timeout=30) as cli:
                cli.send(edge_payload([0], [0]))
                deadline = time.monotonic() + 5
                while not cli.paused and time.monotonic() < deadline:
                    time.sleep(0.005)
                assert cli.paused  # PAUSEd with an EMPTY server queue
                assert bus.snapshot()["gauges"]["ingest.paused"] == 1
                bus.gauge("pipeline.staged_depth", 0)  # engine drained
                cli.send(edge_payload([1], [1]))
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1]


def test_batched_acks_flush_on_idle_and_bye():
    """ack_every > 1 must not strand the tail frames: the idle tick
    (and BYE) flushes the batched-ack remainder, so a client flush()
    after a non-multiple frame count completes instead of timing out."""
    with obs_bus.scope():
        with IngestServer(queue_depth=16, ack_every=3) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port) as cli:
                for i in range(4):  # 4 % 3 != 0: one frame past the batch
                    cli.send(edge_payload([i], [i]))
                assert cli.flush(timeout=5) == 4
        t.join(timeout=5)
        assert [s for s, _ in got] == list(range(4))


def test_payload_to_chunk_validates_vertex_capacity():
    """File-ingest parity on the wire: an out-of-range id raises
    loudly instead of truncating to int32 / vanishing in the fold."""
    from gelly_tpu.ingest.server import payload_to_chunk

    ok = payload_to_chunk(edge_payload([1, 2], [3, 4]), 8,
                          vertex_capacity=8)
    assert int(np.asarray(ok.valid).sum()) == 2
    with pytest.raises(ValueError, match="out of range"):
        payload_to_chunk(edge_payload([1, 70000], [2, 3]), 8,
                         vertex_capacity=1 << 16)
    with pytest.raises(ValueError, match="out of range"):
        payload_to_chunk(edge_payload([-1], [2]), 8, vertex_capacity=8)
    with pytest.raises(ValueError, match="chunk capacity"):
        payload_to_chunk(edge_payload([0, 1, 2], [0, 1, 2]), 2)


def test_ingest_fault_boundary_fires_on_send():
    from gelly_tpu.engine import faults

    with obs_bus.scope():
        with IngestServer(queue_depth=4) as srv:
            with IngestClient("127.0.0.1", srv.port) as cli:
                plan = faults.FaultPlan(
                    [faults.Fault(boundary="ingest", at=0)]
                )
                with faults.install(plan):
                    with pytest.raises(faults.FaultInjected):
                        cli.send(edge_payload([0], [0]))
                assert ("ingest", 0, "raise") in plan.fired


# --------------------------------------------------------------------- #
# pre-compressed DATA frames (the shared compression plane's wire leg)


def _cc_chunks(n_v=1 << 10, chunk=256, chunks=6, seed=3):
    from gelly_tpu.core.chunk import make_chunk

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(chunks):
        s = rng.integers(0, n_v, chunk).astype(np.int64)
        d = rng.integers(0, n_v, chunk).astype(np.int64)
        out.append(make_chunk(s.astype(np.int32), d.astype(np.int32),
                              raw_src=s, raw_dst=d, capacity=chunk,
                              device=False))
    return out


def test_compressed_frames_ride_the_same_contract():
    """DATA_COMPRESSED frames share the seq space with DATA: frames()
    reports the compressed flag per frame, both kinds count into their
    own ``ingest.data_frames_*`` counters, and in-order delivery/acks
    are unchanged."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []

            def run():
                for item in srv.frames():
                    got.append(item)
            t = threading.Thread(target=run, daemon=True)
            t.start()
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send(edge_payload([0], [1]))
                cli.send_compressed({"v": np.arange(3, dtype=np.int32),
                                     "r": np.zeros(3, np.int32)})
                cli.send(edge_payload([2], [3]))
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [(s, c) for s, _p, c in got] == [
            (0, False), (1, True), (2, False)
        ]
        np.testing.assert_array_equal(got[1][1]["v"], np.arange(3))
        snap = bus.snapshot()["counters"]
        assert snap["ingest.data_frames_raw"] == 2
        assert snap["ingest.data_frames_compressed"] == 1


def test_corrupt_compressed_frame_rejected_and_retransmitted():
    """CRC-corrupted DATA_COMPRESSED frame: REJECT + counted, the
    expected seq never advances past the bad bytes, and the client
    retransmits in place — exactly the DATA contract."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            cli.send_compressed({"v": np.asarray([0], np.int32),
                                 "r": np.asarray([0], np.int32)})
            cli.flush(timeout=10)
            body = pack_payload({"v": np.asarray([7], np.int32),
                                 "r": np.asarray([0], np.int32)})
            frame = bytearray(pack_frame(wire.DATA_COMPRESSED, 1, body))
            frame[-1] ^= 0xFF
            with cli._send_lock:
                cli._sock.sendall(bytes(frame))
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_rejected", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.next_seq == 1  # never advanced past bad bytes
            cli.send_compressed({"v": np.asarray([7], np.int32),
                                 "r": np.asarray([0], np.int32)})
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        snap = bus.snapshot()["counters"]
        assert snap["ingest.frames_rejected"] >= 1
        assert [s for s, _ in got] == [0, 1]
        assert got[1][1]["v"].tolist() == [7]


def test_torn_compressed_frame_enqueues_nothing():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            body = pack_payload({"v": np.arange(8, dtype=np.int32),
                                 "r": np.zeros(8, np.int32)})
            frame = pack_frame(wire.DATA_COMPRESSED, 0, body)
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.sendall(frame[: len(frame) - 9])  # torn mid-payload
            raw.close()
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_truncated", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert bus.snapshot()["counters"]["ingest.frames_truncated"] == 1
            assert srv.next_seq == 0
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send_compressed({"v": np.arange(8, dtype=np.int32),
                                     "r": np.zeros(8, np.int32)})
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0]


def test_duplicate_compressed_replay_dropped_and_reacked():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            p0 = {"v": np.asarray([1], np.int32),
                  "r": np.asarray([0], np.int32)}
            cli.send_compressed(p0)
            cli.flush(timeout=10)
            # Replay seq 0 raw (a reconnect race): dropped, re-acked.
            with cli._send_lock:
                cli._sock.sendall(
                    pack_frame(wire.DATA_COMPRESSED, 0, pack_payload(p0))
                )
            cli.send_compressed({"v": np.asarray([2], np.int32),
                                 "r": np.asarray([0], np.int32)})
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1]
        assert bus.snapshot()["counters"]["ingest.frames_duplicate"] == 1


def test_mixed_stream_consumers_fail_loudly():
    """A compressed frame reaching a raw-chunk consumer (and vice
    versa) is a protocol error, not a silent mis-fold."""
    with obs_bus.scope():
        with IngestServer(queue_depth=4) as srv:
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send_compressed({"v": np.asarray([1], np.int32),
                                     "r": np.asarray([0], np.int32)})
                cli.flush(timeout=10)
            with pytest.raises(ValueError, match="compressed DATA frame"):
                next(srv.chunks(capacity=8))
        with IngestServer(queue_depth=4) as srv:
            with IngestClient("127.0.0.1", srv.port) as cli:
                cli.send(edge_payload([0], [1]))
                cli.flush(timeout=10)
            with pytest.raises(ValueError, match="raw DATA frame"):
                next(srv.compressed_payloads())


def test_precompressed_wire_fold_matches_file_ingest():
    """The wire-vs-file bit-identity twin: a client-compressed stream
    folded with ``precompressed=True`` emits window-by-window labels
    identical to the file-ingest codec path over the SAME chunks — and
    the traced serve side shows ZERO compress spans (the stack stage
    carries the staging instead)."""
    from gelly_tpu import obs
    from gelly_tpu.engine.aggregation import run_aggregation
    from gelly_tpu.library.connected_components import (
        connected_components,
    )
    from gelly_tpu.parallel import mesh as mesh_lib

    n_v = 1 << 10
    m1 = mesh_lib.make_mesh(1)
    chunks = _cc_chunks(n_v=n_v, chunk=256, chunks=6)
    agg_file = connected_components(n_v, codec="sparse")
    golden = [
        np.asarray(w) for w in run_aggregation(
            agg_file, chunks, merge_every=2, mesh=m1, ingest_workers=0,
            prefetch_depth=0, h2d_depth=0,
        )
    ]

    agg_wire = connected_components(n_v, codec="sparse")
    payloads = [agg_wire.host_compress(c) for c in chunks]
    tracer = obs.SpanTracer()
    with obs_bus.scope(), obs.install(tracer):
        with IngestServer(queue_depth=16, stop_on_bye=True) as srv:
            def feed():
                with IngestClient("127.0.0.1", srv.port) as cli:
                    for p in payloads:
                        cli.send_compressed(p)
                    cli.flush(timeout=30)
            t = threading.Thread(target=feed, daemon=True)
            t.start()
            wire_windows = [
                np.asarray(w) for w in run_aggregation(
                    agg_wire, srv.compressed_payloads(), merge_every=2,
                    mesh=m1, precompressed=True, ingest_workers=0,
                    prefetch_depth=0, h2d_depth=0,
                )
            ]
            t.join(timeout=30)
    assert len(wire_windows) == len(golden) > 1
    for i, (w, g) in enumerate(zip(wire_windows, golden)):
        assert w.tobytes() == g.tobytes(), f"window {i} diverged"
    # Zero server-side compress spans; the stack stage staged every unit.
    assert tracer.spans("compress") == []
    assert len(tracer.spans("stack")) == len(chunks)


def test_precompressed_validation():
    from gelly_tpu.engine.aggregation import run_aggregation
    from gelly_tpu.library.connected_components import (
        connected_components,
    )

    n_v = 1 << 10
    raw_plan = connected_components(n_v, ingest_combine=False)
    with pytest.raises(ValueError, match="codec-capable"):
        run_aggregation(raw_plan, [], precompressed=True).result()
    # A stack_ordered plan has no producer-compressible wire form
    # (its host_compress ships raw views; the id session is consumer-
    # side stream-order state) — refused like the fused/tenant twins.
    compact = connected_components(n_v, codec="compact",
                                   compact_capacity=n_v)
    with pytest.raises(ValueError, match="ordered stacker"):
        run_aggregation(compact, [], precompressed=True).result()
    codec_plan = connected_components(n_v, codec="sparse")
    with pytest.raises(ValueError, match="merge_every-only"):
        run_aggregation(codec_plan, [], precompressed=True,
                        window_ms=10).result()
    with pytest.raises(ValueError, match="host_precombine"):
        run_aggregation(codec_plan, [], precompressed=True,
                        host_precombine=lambda c: c).result()
    class _Provider:  # quacks like a ShardedEdgeSource
        def stage_units(self, *a, **k):
            return iter(())

    with pytest.raises(ValueError, match="source_provider parses"):
        run_aggregation(codec_plan, [], precompressed=True,
                        source_provider=_Provider()).result()
    # Out-of-range ids in a producer-compressed payload raise at
    # staging (payload_to_chunk parity) — never silently clamp in the
    # device scatter.
    from gelly_tpu.parallel import mesh as mesh_lib

    bad = {"v": np.asarray([n_v + 5], np.int32),
           "r": np.asarray([0], np.int32)}
    with pytest.raises(ValueError, match="out of range"):
        run_aggregation(
            codec_plan, [bad], precompressed=True,
            mesh=mesh_lib.make_mesh(1), ingest_workers=0,
            prefetch_depth=0, h2d_depth=0,
        ).result()


# --------------------------------------------------------------------- #
# STACKED frames: K chunks behind one header/CRC/syscall/fold-dispatch


def test_stacked_body_codec_roundtrip_and_bounds():
    blobs = [pack_payload(edge_payload([i], [i + 1])) for i in range(5)]
    parts = [(b, i % 2 == 0) for i, b in enumerate(blobs)]
    body = wire.pack_stacked(parts)
    out = wire.unpack_stacked(body)
    assert out == parts
    with pytest.raises(wire.FrameError, match="must be 1"):
        wire.pack_stacked([])
    with pytest.raises(wire.FrameError):
        wire.unpack_stacked(body[:-3])  # truncated blob region
    with pytest.raises(wire.FrameError):
        wire.unpack_stacked(body + b"x")  # trailing junk
    bad_kind = bytearray(body)
    bad_kind[2] = 7  # first table entry's kind byte
    with pytest.raises(wire.FrameError, match="kind"):
        wire.unpack_stacked(bytes(bad_kind))


def test_stacked_loopback_in_order_and_tail_drain():
    """stack=K coalesces K sends into one STACKED frame; flush() drains
    the partial tail (the LV203 contract); positions tile the seq space
    exactly as the unstacked wire would have numbered them."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            units: list = []

            def run():
                for item in srv.stacks():
                    units.append(item)
            t = threading.Thread(target=run, daemon=True)
            t.start()
            with IngestClient("127.0.0.1", srv.port, stack=4) as cli:
                for i in range(10):
                    cli.send(edge_payload([i], [i + 1]))
                cli.flush(timeout=10)
                assert cli.acked == 10
                assert cli.unacked_count == 0
        t.join(timeout=5)
        assert [(s, len(p)) for s, p, _ in units] == [(0, 4), (4, 4),
                                                      (8, 2)]
        flat = [p for _, ps, _ in units for p in ps]
        assert [p["src"].tolist() for p in flat] == [[i] for i in
                                                     range(10)]
        snap = bus.snapshot()["counters"]
        assert snap["ingest.frames_stacked"] == 3
        assert snap["ingest.chunks_enqueued"] == 10
        assert snap["ingest.stack_flush_size"] == 2  # tail is untagged
        # 3 frames moved 10 chunks: framing overhead amortized
        # (HELLO + 3 stacked DATA + BYE = 5 frames on the wire).
        assert snap["ingest.frames_received"] <= 5


def test_stacked_age_deadline_flushes_partial_stack():
    """The background age thread ships a lingering partial stack
    without any further send() or flush() call."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port, stack=64,
                              stack_ms=30) as cli:
                cli.send(edge_payload([0], [1]))
                cli.send(edge_payload([1], [2]))
                deadline = time.monotonic() + 5
                while len(got) < 2 and time.monotonic() < deadline:
                    time.sleep(0.01)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1]
        assert bus.snapshot()["counters"]["ingest.stack_flush_age"] >= 1


def test_stacked_byte_ceiling_flushes_before_k():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            with IngestClient("127.0.0.1", srv.port, stack=1000,
                              stack_bytes=1) as cli:
                # Every payload exceeds the 1-byte ceiling on arrival:
                # each send flushes immediately (K=1 → legacy frame).
                cli.send(edge_payload([0], [1]))
                cli.send(edge_payload([1], [2]))
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1]
        assert bus.snapshot()["counters"]["ingest.stack_flush_bytes"] == 2


def _stacked_frame(base, payloads, compressed=False):
    parts = [(pack_payload(p), compressed) for p in payloads]
    return pack_frame(wire.STACKED, base, wire.pack_stacked(parts))


def test_corrupt_stacked_frame_rejected_then_whole_frame_lands():
    """A CRC-corrupt STACKED frame: REJECT + counted, expected seq
    pinned, and the retransmitted WHOLE frame then stages all K
    chunks — frame-granularity retransmit, chunk-granularity state."""
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            frame = _stacked_frame(0, [edge_payload([i], [i])
                                       for i in range(3)])
            bad = bytearray(frame)
            bad[-1] ^= 0xFF
            with cli._send_lock:
                cli._sock.sendall(bytes(bad))
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_rejected", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.next_seq == 0  # pinned below the bad bytes
            with cli._send_lock:
                cli._sock.sendall(frame)
            deadline = time.monotonic() + 5
            while len(got) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1, 2]
        snap = bus.snapshot()["counters"]
        assert snap["ingest.frames_rejected"] >= 1
        assert snap["ingest.frames_stacked"] == 1


def test_torn_stacked_frame_stages_nothing():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            frame = _stacked_frame(0, [edge_payload([i], [i])
                                       for i in range(4)])
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.sendall(frame[: len(frame) - 11])  # torn mid-stack
            raw.close()
            deadline = time.monotonic() + 5
            while (bus.snapshot()["counters"].get(
                    "ingest.frames_truncated", 0) < 1
                    and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.next_seq == 0
            with IngestClient("127.0.0.1", srv.port, stack=4) as cli:
                for i in range(4):
                    cli.send(edge_payload([i], [i]))
                cli.flush(timeout=10)
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1, 2, 3]
        assert bus.snapshot()["counters"]["ingest.frames_stacked"] == 1


def test_duplicate_stacked_replay_dropped_and_reacked():
    with obs_bus.scope() as bus:
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port, stack=3).connect()
            payloads = [edge_payload([i], [i]) for i in range(3)]
            for p in payloads:
                cli.send(p)
            cli.flush(timeout=10)
            # Replay the whole covering frame raw (a reconnect race):
            # dropped whole, re-acked at the stream position.
            with cli._send_lock:
                cli._sock.sendall(_stacked_frame(0, payloads))
            cli.send(edge_payload([9], [9]))
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1, 2, 3]
        snap = bus.snapshot()["counters"]
        assert snap["ingest.frames_duplicate"] == 1
        assert snap["ingest.frames_stacked"] == 1


def test_mixed_stacked_and_unstacked_frames_share_seq_space():
    """Plain DATA and STACKED frames interleave on one connection and
    one sequence space — a client may coalesce opportunistically."""
    with obs_bus.scope():
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port).connect()
            cli.send(edge_payload([0], [0]))          # seq 0, plain
            with cli._send_lock:                       # [1, 4), stacked
                cli._sock.sendall(_stacked_frame(
                    1, [edge_payload([i], [i]) for i in range(1, 4)]))
            deadline = time.monotonic() + 5
            while len(got) < 4 and time.monotonic() < deadline:
                time.sleep(0.01)
            cli._next[None] = 4  # the raw injection advanced the space
            cli.send(edge_payload([4], [4]))          # seq 4, plain
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == [0, 1, 2, 3, 4]
        assert [p["src"].tolist() for _, p in got] == [[i] for i in
                                                       range(5)]


def test_stacked_mid_frame_resume_drops_durable_prefix():
    """THE exactly-once seam: a server restarted at a checkpoint
    position INSIDE a stacked frame re-requests the covering frame and
    stages only the unseen suffix — the durable prefix is dropped, and
    the ACK covers the whole frame so the client releases it."""
    with obs_bus.scope():
        # Restarted incarnation: checkpoint landed at position 2,
        # mid-frame of the client's [0, 4) stacked frame.
        with IngestServer(queue_depth=16, resume_seq=2) as srv:
            units: list = []

            def run():
                for item in srv.stacks():
                    units.append(item)
            t = threading.Thread(target=run, daemon=True)
            t.start()
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.settimeout(5.0)
            raw.sendall(pack_frame(wire.HELLO, 0))
            ftype, seq, _p, _ok = wire.read_frame_checked(raw.recv)
            assert (ftype, seq) == (wire.WELCOME, 2)
            raw.sendall(_stacked_frame(
                0, [edge_payload([i], [i]) for i in range(4)]))
            ftype, seq, _p, _ok = wire.read_frame_checked(raw.recv)
            assert (ftype, seq) == (wire.ACK, 4)  # whole frame released
            deadline = time.monotonic() + 5
            while not units and time.monotonic() < deadline:
                time.sleep(0.01)
            raw.close()
        t.join(timeout=5)
        assert [(s, len(p)) for s, p, _ in units] == [(2, 2)]
        flat = [p["src"].tolist() for _, ps, _ in units for p in ps]
        assert flat == [[2], [3]]  # prefix [0, 2) dropped, never staged


def test_stacked_client_rewinds_covering_frame_on_reconnect():
    """Client side of the mid-frame seam: after a reconnect WELCOME
    whose expected seq lands inside an unacked stacked frame, the
    client retransmits the COVERING frame (its resend buffer is
    frame-granular) and the stream completes exactly-once."""
    with obs_bus.scope():
        with IngestServer(queue_depth=16) as srv:
            got: list = []
            t = _drain(srv, got)
            cli = IngestClient("127.0.0.1", srv.port, stack=4).connect()
            for i in range(4):
                cli.send(edge_payload([i], [i]))
            cli.flush(timeout=10)
            # Drop the connection without BYE and reconnect: the server
            # already staged [0, 4), so the WELCOME re-ack covers the
            # frame; then keep streaming stacked.
            cli._teardown_socket()
            cli.reconnect()
            for i in range(4, 8):
                cli.send(edge_payload([i], [i]))
            cli.flush(timeout=10)
            cli.close()
        t.join(timeout=5)
        assert [s for s, _ in got] == list(range(8))


def test_stacked_fold_bit_identical_and_one_dispatch_per_frame():
    """Acceptance twin: a stacked compressed wire stream folds
    bit-identically to the unstacked file-ingest path, AND the engine
    dispatches exactly ONE fold per wire frame (the staged unit rides
    ``fold_codec``'s stacked dispatch whole)."""
    from gelly_tpu import obs
    from gelly_tpu.engine.aggregation import run_aggregation
    from gelly_tpu.library.connected_components import (
        connected_components,
    )
    from gelly_tpu.parallel import mesh as mesh_lib

    n_v = 1 << 10
    m1 = mesh_lib.make_mesh(1)
    chunks = _cc_chunks(n_v=n_v, chunk=256, chunks=6)
    agg_file = connected_components(n_v, codec="sparse")
    golden = [
        np.asarray(w) for w in run_aggregation(
            agg_file, chunks, merge_every=6, mesh=m1, ingest_workers=0,
            prefetch_depth=0, h2d_depth=0,
        )
    ]

    agg_wire = connected_components(n_v, codec="sparse")
    payloads = [agg_wire.host_compress(c) for c in chunks]
    tracer = obs.SpanTracer()
    with obs_bus.scope() as bus, obs.install(tracer):
        with IngestServer(queue_depth=16, stop_on_bye=True) as srv:
            def feed():
                with IngestClient("127.0.0.1", srv.port,
                                  stack=3) as cli:
                    for p in payloads:
                        cli.send_compressed(p)
                    cli.flush(timeout=30)
            t = threading.Thread(target=feed, daemon=True)
            t.start()
            wire_windows = [
                np.asarray(w) for w in run_aggregation(
                    agg_wire, srv.compressed_payload_units(),
                    merge_every=6, fold_batch=3, mesh=m1,
                    precompressed=True, ingest_workers=0,
                    prefetch_depth=0, h2d_depth=0,
                )
            ]
            t.join(timeout=30)
        snap = bus.snapshot()["counters"]
    assert len(wire_windows) == len(golden) >= 1
    for i, (w, g) in enumerate(zip(wire_windows, golden)):
        assert w.tobytes() == g.tobytes(), f"window {i} diverged"
    # ONE fold dispatch per wire frame: 6 chunks in 2 stacked frames.
    assert snap["ingest.frames_stacked"] == 2
    assert snap["engine.units_folded"] == 2
    assert snap["engine.chunks_folded"] == 6
    assert len(tracer.spans("fold")) == 2
    assert tracer.spans("compress") == []  # producer-compressed


# --------------------------------------------------------------------- #
# SIGKILL'd server: no double-fold of acked chunks (slow; CI ingest lane)


CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_ingest_crash_child.py")


def _spawn_server_child(ckpt, port_file, out, total, sleep_s,
                        mode="raw", framing="plain"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(port_file), str(out),
         str(total), str(sleep_s), mode, framing],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _wait_port(port_file, proc, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server child exited rc={proc.returncode} before "
                "publishing its port"
            )
        if os.path.exists(port_file):
            return int(open(port_file).read())
        time.sleep(0.02)
    raise AssertionError("server child never published its port")


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.parametrize("mode,stack", [
    ("raw", 1), ("compressed", 1), ("raw", 3), ("compressed", 3),
])
def test_sigkilled_server_never_double_folds_acked_chunks(
        tmp_path, mode, stack):
    """``mode="compressed"`` runs the same SIGKILL protocol over
    CLIENT-COMPRESSED DATA_COMPRESSED frames (sparse CC pairs): acked
    compressed chunks must never double-fold either — same seq space,
    same checkpoint-gated ack contract.

    ``stack=3`` reruns the matrix with a coalescing client: 3 is
    coprime with the child's ``CKPT_EVERY=4``, so durable checkpoint
    positions land MID-frame and the kill/restart exercises the
    covering-frame redelivery + durable-prefix-drop seam — stacking
    must be invisible to exactly-once."""
    import _ingest_crash_child as child_mod

    compressed = mode == "compressed"
    framing = "stacked" if stack > 1 else "plain"
    rng = np.random.default_rng(23)
    total = 64

    def mk_payload():
        src = rng.integers(0, child_mod.N_V, 32)
        dst = rng.integers(0, child_mod.N_V, 32)
        if compressed:
            from gelly_tpu.library.connected_components import (
                cc_pairs_numpy,
            )

            v, r = cc_pairs_numpy(src, dst, None, child_mod.N_V)
            return {"v": v, "r": r}
        return edge_payload(src, dst)

    payloads = [mk_payload() for _ in range(total)]
    # Golden: the same fold, in-process, uninterrupted.
    golden = child_mod.init_state()
    for p in payloads:
        golden = child_mod.fold(golden, p)

    ckpt = tmp_path / "ckpt"
    port_file = str(tmp_path / "port")
    out = str(tmp_path / "final.npz")

    p1 = _spawn_server_child(ckpt, port_file, out, total, 0.03, mode,
                             framing)
    port = _wait_port(port_file, p1)
    cli = IngestClient("127.0.0.1", port, send_pause_timeout=60,
                       stack=stack)
    cli.connect()

    sent = 0
    send_died = threading.Event()

    def sender():
        nonlocal sent
        from gelly_tpu.ingest.client import IngestError

        while sent < total:
            try:
                cli.send(payloads[sent], compressed=compressed)
                sent += 1
            except IngestError:
                # The failed send is already BUFFERED (resend-buffer
                # contract): reconnect() will deliver it — count it.
                sent += 1
                send_died.set()
                return

    t = threading.Thread(target=sender, daemon=True)
    t.start()

    # Kill once at least two durable checkpoints exist and acks flowed.
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if len(list(ckpt.glob("ckpt-*.npz"))) >= 2 and cli.acked >= 8:
            break
        time.sleep(0.02)
    else:
        pytest.fail("no checkpoints/acks before the deadline")
    acked_before_kill = cli.acked
    os.kill(p1.pid, signal.SIGKILL)
    assert p1.wait(timeout=60) == -signal.SIGKILL
    assert not os.path.exists(out)  # died mid-stream
    t.join(timeout=60)

    # Restart: the new incarnation resumes the SEQUENCE at its newest
    # valid checkpoint; the client reconnects and resends exactly the
    # unacked suffix.
    os.unlink(port_file)
    p2 = _spawn_server_child(ckpt, port_file, out, total, 0.0, mode,
                             framing)
    cli.port = _wait_port(port_file, p2)
    deadline = time.monotonic() + 60
    while True:
        try:
            cli.reconnect()
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    assert cli.acked >= acked_before_kill  # acked work never rewinds

    while sent < total:  # finish the stream
        cli.send(payloads[sent], compressed=compressed)
        sent += 1
    cli.flush(timeout=120)
    cli.close()
    assert p2.wait(timeout=180) == 0

    from gelly_tpu.engine.checkpoint import load_checkpoint

    final, pos, _ = load_checkpoint(out, like=child_mod.init_state())
    assert pos == total
    # THE exactly-once assertion: counters (non-idempotent) exact.
    key = "v" if compressed else "src"
    assert int(final["chunks"]) == total
    assert int(final["edges"]) == sum(
        int(p[key].shape[0]) for p in payloads
    )
    np.testing.assert_array_equal(child_mod.labels(final),
                                  child_mod.labels(golden))
    assert final["parent"].tobytes() == golden["parent"].tobytes()
