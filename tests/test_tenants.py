"""Multi-tenant batched fold engine (``gelly_tpu/engine/tenants.py``).

The acceptance contract of the tenant batch: for EVERY tenant of a
mixed-workload N >= 64 batch, labels are bit-identical to that
tenant's single-stream ``run_aggregation`` run; one vmapped dispatch
advances the whole tier per scheduling round; live ``labels(tenant,
v)`` queries are answered from the last merge-window snapshot and
never block (or are blocked by) a window close; per-tenant
checkpoints ride the existing position-header/CRC format and resume
exactly-once under kill -9 (crash child).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.engine.checkpoint import load_checkpoint
from gelly_tpu.engine.resilience import CheckpointManager
from gelly_tpu.engine.tenants import MultiTenantEngine, TenantBatch
from gelly_tpu.library.connected_components import (
    cc_tenant_tier,
    connected_components,
)
from gelly_tpu.library.degrees import degree_aggregate
from gelly_tpu.obs import bus as obs_bus

pytestmark = pytest.mark.tenants

N_V = 128
CHUNK = 32


def _stream(seed: int, n_edges: int = 96, n_v: int = N_V,
            chunk: int = CHUNK, identity: bool = False):
    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, n_v, (n_edges, 2))
    kw = {"table": IdentityVertexTable(n_v)} if identity else {}
    return edge_stream_from_edges(
        [(int(a), int(b)) for a, b in pairs],
        vertex_capacity=n_v, chunk_size=chunk, **kw,
    )


def _cc_plan(n_v: int = N_V):
    return connected_components(n_v, merge="gather", ingest_combine=False)


# --------------------------------------------------------------------- #
# batched fold correctness


def test_mixed_workload_64_tenants_bit_identical():
    """The acceptance batch: 64 tenants across two tiers (CC +
    degrees), every tenant's final snapshot bit-identical to its
    single-stream run_aggregation run."""
    cc = _cc_plan()
    dg = degree_aggregate(N_V, ingest_combine=False)
    eng = MultiTenantEngine(merge_every=2)
    eng.add_tier("cc", cc, CHUNK)
    eng.add_tier("deg", dg, CHUNK)
    n_cc, n_dg = 48, 16
    for i in range(n_cc):
        eng.admit(("cc", i), "cc", chunks=_stream(i))
    for i in range(n_dg):
        eng.admit(("dg", i), "deg", chunks=_stream(1000 + i))
    out = eng.drain()
    assert len(out) == 64
    for i in range(n_cc):
        want = np.asarray(_stream(i).aggregate(cc, merge_every=2).result())
        got = out[("cc", i)]
        assert got.dtype == want.dtype
        assert got.tobytes() == want.tobytes()
    for i in range(n_dg):
        want = np.asarray(
            _stream(1000 + i).aggregate(dg, merge_every=2).result()
        )
        got = out[("dg", i)]
        assert got.dtype == want.dtype
        assert got.tobytes() == want.tobytes()


def test_one_dispatch_advances_the_whole_tier():
    """The amortization claim: D tenants × K chunks fold in K
    dispatches (one per scheduling round), not D × K."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=2)
    eng.add_tier("cc", cc, CHUNK)
    n, chunks_each = 8, 3  # 96 edges / CHUNK 32
    for i in range(n):
        eng.admit(i, "cc", chunks=_stream(i))
    eng.drain()
    assert eng.stats["chunks"] == n * chunks_each
    assert eng.stats["dispatches"] == chunks_each


def test_uneven_streams_and_starvation_accounting():
    """Stragglers never stall the batch: tenants with shorter streams
    finish early (masked no-op lanes), longer ones keep advancing;
    results stay bit-identical per tenant."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    lengths = {0: 32, 1: 96, 2: 160, 3: 64}
    for tid, n in lengths.items():
        eng.admit(tid, "cc", chunks=_stream(tid, n_edges=n))
    out = eng.drain()
    for tid, n in lengths.items():
        want = np.asarray(
            _stream(tid, n_edges=n).aggregate(cc, merge_every=1).result()
        )
        assert out[tid].tobytes() == want.tobytes()
        assert eng.position(tid) == -(-n // CHUNK)
    # The longest tenant drove 5 rounds; everyone else went masked for
    # the tail rounds but was already `finished`, so nobody starved.
    assert eng.stats["dispatches"] == 5
    assert eng.stats["starved_lanes"] == 0


def test_starved_windows_counts_live_but_empty_lanes():
    """A live push-mode tenant with nothing queued contributes a
    masked lane — counted as a starved window on the bus."""
    cc = _cc_plan()
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1)
        eng.add_tier("cc", cc, CHUNK)
        eng.admit("busy", "cc")
        eng.admit("idle", "cc")
        for c in _stream(3):
            eng.submit("busy", c)
        eng.finish("busy")
        with pytest.raises(RuntimeError, match="never finish"):
            eng.drain()  # idle is live push-mode: loud, not a hang
        assert eng.starved_windows("idle") > 0
        assert bus.counters["tenants.starved_windows"] > 0
        eng.finish("idle")
        out = eng.drain()
        want = np.asarray(_stream(3).aggregate(cc, merge_every=1).result())
        assert out["busy"].tobytes() == want.tobytes()


def test_lane_width_growth_preserves_admitted_state():
    """Admissions double the lane width (1 → 2 → 4 …); existing
    tenants' summaries survive the widening copy bit-identically."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    eng.admit(0, "cc")
    for c in _stream(0):
        eng.submit(0, c)
    eng.finish(0)
    # Drive tenant 0 to completion at width 1, then admit more.
    for tid in range(1, 5):
        eng.admit(tid, "cc", chunks=_stream(tid))
    assert eng._tiers["cc"].batch.lanes >= 5  # widened past 1
    out = eng.drain()
    for tid in range(5):
        want = np.asarray(
            _stream(tid).aggregate(cc, merge_every=1).result()
        )
        assert out[tid].tobytes() == want.tobytes()


def test_mesh_shards_the_tenant_axis():
    from gelly_tpu.parallel import mesh as mesh_lib

    cc = _cc_plan()
    m = mesh_lib.make_mesh()
    eng = MultiTenantEngine(merge_every=2, mesh=m)
    eng.add_tier("cc", cc, CHUNK)
    n = 10  # lanes pad to 16 (multiple of the 8-device mesh)
    for i in range(n):
        eng.admit(i, "cc", chunks=_stream(i))
    out = eng.drain()
    assert eng._tiers["cc"].batch.lanes % mesh_lib.num_shards(m) == 0
    for i in range(n):
        want = np.asarray(_stream(i).aggregate(cc, merge_every=2).result())
        assert out[i].tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# plan validation


def test_tier_refuses_stateful_codec_plans():
    """Only the genuinely un-batchable case stays refused now that
    codec plans compile a vmapped fold_codec — and the message names
    the reason: the stack_ordered session assigns compact ids in
    global stream order, which concurrent lanes cannot provide."""
    from gelly_tpu.engine.aggregation import _compiled_tenant_plan

    compact = connected_components(N_V, codec="compact",
                                   compact_capacity=N_V)
    with pytest.raises(ValueError, match="GLOBAL STREAM order"):
        _compiled_tenant_plan(compact, 2)
    # A codec-ONLY plan on a raw tier is refused up front too (its raw
    # fold does not exist), pointing at the compressed-tier knob.
    with pytest.raises(ValueError, match="compressed=True"):
        TenantBatch(compact, CHUNK)
    # Plain codec plans now compile fold_codec next to the raw fold.
    sparse = connected_components(N_V, codec="sparse")
    plan = _compiled_tenant_plan(sparse, 2)
    assert plan.fold_codec is not None


def test_tier_refuses_host_transforms():
    from gelly_tpu.engine.aggregation import _compiled_tenant_plan

    agg = _cc_plan()
    agg.jit_transform = False
    with pytest.raises(ValueError, match="host-side transform"):
        _compiled_tenant_plan(agg, 2)


def test_admission_validation():
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    with pytest.raises(ValueError, match="already registered"):
        eng.add_tier("cc", cc, CHUNK)
    with pytest.raises(ValueError, match="unknown tier"):
        eng.admit(0, "nope")
    eng.admit(0, "cc")
    with pytest.raises(ValueError, match="already admitted"):
        eng.admit(0, "cc")
    with pytest.raises(ValueError, match="chunk capacity"):
        eng.submit(0, next(iter(_stream(0, chunk=CHUNK * 2))))
    eng.finish(0)
    with pytest.raises(ValueError, match="finished"):
        eng.submit(0, next(iter(_stream(0))))


def test_cc_tenant_tier_builder():
    agg, cap = cc_tenant_tier(N_V, chunk_capacity=CHUNK)
    assert agg.host_compress is None  # raw fold, vmappable
    assert cap == CHUNK
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("t", agg, cap)
    eng.admit(0, "t", chunks=_stream(0))
    out = eng.drain()
    want = np.asarray(_stream(0).aggregate(agg, merge_every=1).result())
    assert out[0].tobytes() == want.tobytes()


def test_delta_auto_rows_knob():
    agg = connected_components(N_V, delta_auto_rows=777)
    assert agg.merge_delta_auto_rows == 777
    agg = connected_components(N_V)
    assert agg.merge_delta_auto_rows == N_V // 4
    agg = connected_components(N_V, codec="compact", compact_capacity=64,
                               delta_auto_rows=11)
    assert agg.merge_delta_auto_rows == 11


# --------------------------------------------------------------------- #
# live queries


def test_query_staleness_is_one_merge_window():
    """Mid-stream queries answer from the LAST closed window — stale by
    at most one window — and carry the window number."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    eng.admit(0, "cc")
    assert eng.labels(0) is None  # no window closed yet
    assert eng.snapshot_window(0) == 0
    chunks = list(_stream(0, n_edges=160))
    seen_windows = []
    eng.start()
    try:
        for k, c in enumerate(chunks):
            eng.submit(0, c)
            # merge_every=1: chunk k+1's fold closes window k+1 in the
            # same scheduling round — wait for it (first round pays the
            # vmapped-plan compile), then the snapshot must be exactly
            # one-window fresh.
            deadline = time.time() + 60
            while (time.time() < deadline
                   and eng.snapshot_window(0) < k + 1):
                time.sleep(0.01)
            seen_windows.append(eng.snapshot_window(0))
            # A scalar labels() read mid-stream.
            v = eng.labels(0, 0)
            assert v is not None and v.shape == ()
        eng.finish(0)
        deadline = time.time() + 20
        while time.time() < deadline and eng.position(0) < len(chunks):
            time.sleep(0.02)
    finally:
        eng.stop()
    assert seen_windows == list(range(1, len(chunks) + 1))
    want = np.asarray(
        _stream(0, n_edges=160).aggregate(cc, merge_every=1).result()
    )
    assert eng.labels(0).tobytes() == want.tobytes()


def test_queries_never_block_window_close():
    """Hammer queries from two threads through a whole drain: every
    window still closes (drain terminates) and every observed snapshot
    is internally consistent (labels row matches a prefix run)."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    for i in range(4):
        eng.admit(i, "cc", chunks=_stream(i, n_edges=256))
    stop = threading.Event()
    errors: list = []

    def hammer():
        try:
            while not stop.is_set():
                for i in range(4):
                    eng.labels(i)
                    eng.snapshot_window(i)
                    eng.queue_depth(i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        out = eng.drain()
    finally:
        stop.set()
        for t in threads:
            t.join(5)
    assert not errors
    for i in range(4):
        want = np.asarray(
            _stream(i, n_edges=256).aggregate(cc, merge_every=1).result()
        )
        assert out[i].tobytes() == want.tobytes()


def test_query_for_tenant_admitted_after_snapshot_returns_none():
    """A tenant admitted AFTER the last window close has no lane in
    the stored snapshot — query must return None, not CLAMP to the
    highest stacked lane (JAX out-of-bounds indexing clamps instead of
    raising, which silently leaked another tenant's row)."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    eng.admit("a", "cc", chunks=_stream(0))
    a_row = eng.drain()["a"]
    assert a_row is not None
    # "b" lands on lane 1; the snapshot is still the width-1 stack
    # from the drain above.
    eng.admit("b", "cc")
    assert eng.labels("b") is None
    assert eng.labels("b", 0) is None
    assert eng.snapshot_window("b") == 0
    assert eng.labels("a").tobytes() == a_row.tobytes()  # "a" unharmed
    # Once "b" folds its own stream, queries resolve to b's own data.
    for c in _stream(7):
        eng.submit("b", c)
    eng.finish("b")
    out = eng.drain()
    want = np.asarray(_stream(7).aggregate(cc, merge_every=1).result())
    assert out["b"].tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# malformed-chunk containment


def test_submit_rejects_template_mismatch_to_submitter():
    """A chunk whose ``val`` dtype diverges from the tier template is
    rejected AT submit() — were it first caught at stack time it would
    kill the scheduler thread for every tenant, after the round had
    already popped (and so dropped) other tenants' chunks."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    eng.admit("good", "cc")
    eng.admit("bad", "cc")
    chunks = list(_stream(3))
    for c in chunks:
        eng.submit("good", c)
    rogue = chunks[0]._replace(
        val=np.asarray(chunks[0].val, np.float64)
    )
    with pytest.raises(ValueError, match="tier template"):
        eng.submit("bad", rogue)
    eng.finish("good")
    eng.finish("bad")
    out = eng.drain()
    want = np.asarray(_stream(3).aggregate(cc, merge_every=1).result())
    assert out["good"].tobytes() == want.tobytes()


def test_pull_mode_malformed_chunk_quarantines_one_tenant():
    """A pull-source tenant shipping a template-mismatched chunk is
    quarantined (its stream truncated at the bad chunk) — the
    scheduler survives and every other tenant folds to completion."""
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    rogue = [c._replace(val=np.asarray(c.val, np.float64))
             for c in _stream(5, n_edges=64)]
    eng.admit("good", "cc", chunks=_stream(0))
    eng.admit("rogue", "cc", chunks=rogue)
    out = eng.drain()  # terminates: the bad tenant must not hang it
    want = np.asarray(_stream(0).aggregate(cc, merge_every=1).result())
    assert out["good"].tobytes() == want.tobytes()
    assert eng.position("rogue") == 0  # nothing folded past the reject


def test_starved_windows_counts_only_dispatch_rounds():
    """The counter's unit is 'masked no-op lane IN a dispatch': rounds
    where nothing dispatched must not bump it (an idle serving engine
    polling empty queues would otherwise inflate it at the poll
    rate, diverging from the bus counter)."""
    cc = _cc_plan()
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1)
        eng.add_tier("cc", cc, CHUNK)
        eng.admit("busy", "cc")
        eng.admit("idle", "cc")
        for c in _stream(3):  # 96 edges -> 3 chunks -> 3 rounds
            eng.submit("busy", c)
        eng.finish("busy")
        with pytest.raises(RuntimeError, match="never finish"):
            eng.drain()
        # Exactly one starved window per DISPATCH round; the empty
        # round that ended drain() contributes none.
        assert eng.starved_windows("idle") == 3
        assert bus.counters["tenants.starved_windows"] == 3
        assert eng.stats["starved_lanes"] == 3


# --------------------------------------------------------------------- #
# per-tenant checkpoints + resume


def test_checkpoint_manager_prefix_isolates_rotations(tmp_path):
    a = CheckpointManager(str(tmp_path), prefix="t1", async_write=False,
                          keep=2)
    b = CheckpointManager(str(tmp_path), prefix="t11", async_write=False,
                          keep=2)
    for pos in (1, 2, 3):
        a.save({"x": np.full((4,), pos)}, pos)
    b.save({"x": np.full((4,), 99)}, 7)
    assert [os.path.basename(p) for p in a.list()] == [
        "t1-000000000002.npz", "t1-000000000003.npz",
    ]
    assert [os.path.basename(p) for p in b.list()] == [
        "t11-000000000007.npz",
    ]
    got = a.load_latest(like={"x": np.zeros((4,), np.int64)})
    assert got is not None and got[1] == 3
    with pytest.raises(ValueError, match="prefix"):
        CheckpointManager(str(tmp_path), prefix=f"a{os.sep}b")
    # "-" is the rotation separator: a prefix containing it would glob
    # into sibling rotations ("t7-*" matches a "t7-0" tenant's files).
    with pytest.raises(ValueError, match="prefix"):
        CheckpointManager(str(tmp_path), prefix="t7-0")


def test_tenant_prefixes_escape_arbitrary_ids(tmp_path):
    from gelly_tpu.engine.tenants import tenant_prefix

    # Injective + separator-free: ids "7" and "7-0" must never share a
    # rotation namespace (the raw f"t{id}" form made t7's glob match,
    # prune and even load t7-0's checkpoints).
    assert tenant_prefix(7) == "t7"
    assert tenant_prefix("7-0") == "t7%2d0"
    assert "-" not in tenant_prefix("user-42/7%x")
    assert tenant_prefix("a-b") != tenant_prefix("a_b")
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=1, checkpoint_dir=str(tmp_path))
    eng.add_tier("cc", cc, CHUNK)
    eng.admit("7", "cc", chunks=_stream(0, n_edges=32))
    eng.admit("7-0", "cc", chunks=_stream(1, n_edges=64))
    eng.drain()
    t7 = eng._tenants["7"].manager.list()
    t70 = eng._tenants["7-0"].manager.list()
    assert t7 and t70 and not set(t7) & set(t70)
    # Each rotation resolves to ITS tenant's position.
    assert load_checkpoint(t7[-1])[1] == 1
    assert load_checkpoint(t70[-1])[1] == 2


def test_per_tenant_checkpoints_ride_the_crc_format(tmp_path):
    cc = _cc_plan()
    eng = MultiTenantEngine(merge_every=2, checkpoint_dir=str(tmp_path),
                            checkpoint_every=1)
    eng.add_tier("cc", cc, CHUNK)
    for i in range(3):
        eng.admit(i, "cc", chunks=_stream(i))
    eng.drain()
    for i in range(3):
        files = sorted(tmp_path.glob(f"t{i}-*.npz"))
        assert files, i
        state, pos, meta = load_checkpoint(
            str(files[-1]), like=cc.init()
        )
        assert pos == eng.position(i) == 3
        assert meta["tenant"] == str(i)
        assert meta["tier"] == "cc"


def test_resume_skips_folded_prefix_bit_identical(tmp_path):
    """Kill-free resume: a first engine folds a prefix (checkpoints
    on), a second engine with resume=True folds only the remainder and
    ends bit-identical to an uninterrupted run."""
    cc = _cc_plan()
    chunks = {i: list(_stream(i, n_edges=256)) for i in range(3)}
    eng = MultiTenantEngine(merge_every=1, checkpoint_dir=str(tmp_path),
                            checkpoint_every=1)
    eng.add_tier("cc", cc, CHUNK)
    for i in range(3):
        eng.admit(i, "cc", chunks=chunks[i][:5])  # prefix only
    eng.drain()
    eng2 = MultiTenantEngine(merge_every=1, checkpoint_dir=str(tmp_path),
                             checkpoint_every=1, resume=True)
    eng2.add_tier("cc", cc, CHUNK)
    for i in range(3):
        eng2.admit(i, "cc", chunks=chunks[i])  # full source, seekable
    out = eng2.drain()
    assert eng2.stats["chunks"] == 3 * 3  # only the 3-chunk suffixes
    for i in range(3):
        want = np.asarray(
            _stream(i, n_edges=256).aggregate(cc, merge_every=1).result()
        )
        assert out[i].tobytes() == want.tobytes()


CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_tenants_crash_child.py")


def _spawn(ckpt_dir, out, sleep_s, compressed=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single default CPU device is enough
    if compressed:
        env["GELLY_TEN_COMPRESSED"] = "1"
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt_dir), str(out), str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.faults
@pytest.mark.parametrize("compressed", [False, True],
                         ids=["raw", "codec"])
def test_multi_tenant_kill9_resume_bit_identical(tmp_path, compressed):
    """SIGKILL a multi-tenant run mid-window; the resumed incarnation's
    final forest must be bit-identical, per tenant, to the unkilled
    run AND to each tenant's single-stream run_aggregation oracle. The
    ``codec`` variant runs a COMPRESSED tier (producer-side compress +
    fold_codec lanes): the per-tenant payload-position resume must be
    exactly-once too."""
    import _tenants_crash_child as child

    out_clean = tmp_path / "clean.npz"
    out_resumed = tmp_path / "resumed.npz"
    ckpt_clean = tmp_path / "ck-clean"
    ckpt = tmp_path / "ck"

    p = _spawn(ckpt_clean, out_clean, 0.0, compressed=compressed)
    assert p.wait(timeout=300) == 0

    p = _spawn(ckpt, out_resumed, 0.03, compressed=compressed)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if p.poll() is not None:
            pytest.fail(f"child exited early (rc={p.returncode})")
        # Kill only after EVERY tenant has a durable checkpoint, so
        # resume exercises all three rotations.
        if all(
            list(ckpt.glob(f"t{t}-*.npz"))
            for t in range(child.TENANTS)
        ) if ckpt.exists() else False:
            break
        time.sleep(0.02)
    else:
        pytest.fail("no per-tenant checkpoints appeared before deadline")
    os.kill(p.pid, signal.SIGKILL)
    assert p.wait(timeout=60) == -signal.SIGKILL
    assert not out_resumed.exists()

    total = -(-child.N_EDGES // child.CHUNK)
    for t in range(child.TENANTS):
        newest = sorted(ckpt.glob(f"t{t}-*.npz"))[-1]
        _, pos, _ = load_checkpoint(str(newest))
        assert 0 < pos < total  # killed mid-stream for every tenant

    p = _spawn(ckpt, out_resumed, 0.0, compressed=compressed)
    assert p.wait(timeout=300) == 0
    resumed, _, _ = load_checkpoint(str(out_resumed))
    clean, _, _ = load_checkpoint(str(out_clean))
    assert len(resumed) == len(clean) == child.TENANTS
    for t in range(child.TENANTS):
        assert resumed[t].tobytes() == clean[t].tobytes()
        # The unkilled single-stream oracle (always the RAW plan: the
        # compressed tier's labels must match it bit-for-bit anyway).
        agg, _cap = cc_tenant_tier(child.N_V, chunk_capacity=child.CHUNK)
        want = np.asarray(
            child.build_stream(t).aggregate(agg, merge_every=2).result()
        )
        assert resumed[t].tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# observability


def test_bus_gauges_and_counters():
    cc = _cc_plan()
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=2)
        eng.add_tier("cc", cc, CHUNK)
        for i in range(4):
            eng.admit(i, "cc", chunks=_stream(i))
        eng.drain()
        snap = bus.snapshot()
        assert snap["counters"]["tenants.dispatches"] == 3
        assert snap["counters"]["tenants.chunks_folded"] == 12
        assert snap["counters"]["tenants.windows_closed"] >= 1
        assert "tenants.active" in snap["gauges"]
        assert "tenants.queue_depth" in snap["gauges"]


def test_heartbeat_carries_tenant_fields():
    from gelly_tpu.obs import SpanTracer, install

    cc = _cc_plan()
    tracer = SpanTracer(heartbeat_every_s=0.0)  # beat on every tick
    with obs_bus.scope():
        with install(tracer):
            eng = MultiTenantEngine(merge_every=1)
            eng.add_tier("cc", cc, CHUNK)
            for i in range(2):
                eng.admit(i, "cc", chunks=_stream(i))
            eng.drain()
    beats = [i for i in tracer.instants() if i["name"] == "heartbeat"]
    assert beats
    line = beats[-1]["args"]
    assert "tenants_active" in line
    assert "tenants_queue_depth" in line
    assert "starved" in line
    assert "backlog_age_max_s" in line
    assert line["slo_breaching"] == 0  # no plane attached, gauge absent
    folds = [s for s in tracer.spans() if s["name"] == "fold"]
    assert folds and all(
        s["args"]["lanes"] >= s["args"]["advanced"] for s in folds
    )


def test_attached_slo_plane_ticks_from_scheduler():
    """An attached SLO plane is evaluated inside the dispatch loop's
    rate-limited gauge block: per-tenant burn-rate gauges and the
    ``slo.breaching`` headline gauge exist after a drain, and the
    heartbeat mirrors the count without a second evaluation thread."""
    from gelly_tpu.obs import SpanTracer, install
    from gelly_tpu.obs.slo import SloPlane, tenant_backlog_age_s

    cc = _cc_plan()
    tracer = SpanTracer(heartbeat_every_s=0.0)
    with obs_bus.scope() as bus:
        with install(tracer):
            eng = MultiTenantEngine(merge_every=1)
            # Impossible-to-breach threshold: the assertion is about
            # plumbing (gauges published from the scheduler), not about
            # forcing a breach (test_slo.py covers breaches).
            eng.attach_slo_plane(
                SloPlane([tenant_backlog_age_s(1e9)], bus=bus))
            eng.add_tier("cc", cc, CHUNK)
            for i in range(2):
                eng.admit(i, "cc", chunks=_stream(i))
            eng.drain()
        snap = bus.snapshot()["gauges"]
        assert snap.get("slo.breaching") == 0
        burn = [k for k in snap if k.endswith(".burn_rate")]
        assert any(".t0" in k for k in burn) and any(
            ".t1" in k for k in burn)
    beats = [i for i in tracer.instants() if i["name"] == "heartbeat"]
    assert beats and beats[-1]["args"]["slo_breaching"] == 0


# --------------------------------------------------------------------- #
# compressed tiers (the shared compression plane's tenant leg)


def _compressed_tier():
    return cc_tenant_tier(N_V, chunk_capacity=CHUNK, compressed=True,
                          codec="sparse")


def test_compressed_tier_bit_identical_to_raw_tier():
    """Tenants shipping producer-compressed payloads fold through the
    vmapped fold_codec and every final snapshot is bit-identical to
    the raw tier's (and to the single-stream oracle); dispatches land
    on ``tenants.compressed_dispatches``."""
    def chunk_lists(t):
        return list(_stream(300 + t))

    agg_r, cap = cc_tenant_tier(N_V, chunk_capacity=CHUNK)
    eng_r = MultiTenantEngine(merge_every=2)
    eng_r.add_tier("cc", agg_r, cap)
    for t in range(4):
        eng_r.admit(t, "cc", chunks=chunk_lists(t))
    raw = eng_r.drain()

    agg_c, cap = _compressed_tier()
    eng_c = MultiTenantEngine(merge_every=2)
    eng_c.add_tier("cc", agg_c, cap, compressed=True)
    with obs_bus.scope() as bus:
        for t in range(4):
            eng_c.admit(t, "cc", chunks=[
                agg_c.host_compress(c) for c in chunk_lists(t)
            ])
        comp = eng_c.drain()
    counters = bus.snapshot()["counters"]
    assert counters["tenants.compressed_dispatches"] >= 1
    assert counters["tenants.compressed_dispatches"] == \
        counters["tenants.dispatches"]
    for t in range(4):
        assert comp[t].dtype == raw[t].dtype
        assert comp[t].tobytes() == raw[t].tobytes()


def test_compressed_tier_push_mode_and_uneven_streams():
    """submit_payload from the producer thread; uneven backlogs ride
    masked identity-payload lanes without disturbing neighbors."""
    agg, cap = _compressed_tier()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", agg, cap, compressed=True)
    chunks_a = list(_stream(31, n_edges=4 * CHUNK))
    chunks_b = list(_stream(32, n_edges=CHUNK))  # 4x shorter
    eng.admit("a", "cc")
    eng.admit("b", "cc")
    for c in chunks_a:
        eng.submit_payload("a", agg.host_compress(c))
    for c in chunks_b:
        eng.submit_payload("b", agg.host_compress(c))
    eng.finish("a")
    eng.finish("b")
    out = eng.drain()
    for tid, chunks in (("a", chunks_a), ("b", chunks_b)):
        want = np.asarray(run_aggregation_oracle(chunks))
        assert out[tid].tobytes() == want.tobytes()


def run_aggregation_oracle(chunks):
    from gelly_tpu.engine.aggregation import run_aggregation

    agg = _cc_plan()
    return run_aggregation(
        agg, chunks, merge_every=1, ingest_workers=0,
        prefetch_depth=0, h2d_depth=0,
    ).result()


def test_compressed_tier_guards():
    agg_c, cap = _compressed_tier()
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", agg_c, cap, compressed=True)
    eng.add_tier("raw", _cc_plan(), cap)
    eng.admit("c", "cc")
    eng.admit("r", "raw")
    chunk = next(iter(_stream(5)))
    # raw chunk into a compressed tier / payload into a raw tier
    with pytest.raises(ValueError, match="compressed tier"):
        eng.submit("c", chunk)
    with pytest.raises(ValueError, match="raw tier"):
        eng.submit_payload("r", agg_c.host_compress(chunk))
    # an EdgeChunk smuggled through submit_payload is named loudly
    with pytest.raises(ValueError, match="EdgeChunk"):
        eng.submit_payload("c", chunk)
    # payload template mismatch raises to the SUBMITTER, not the
    # scheduler: first payload pins the codec shape
    eng.submit_payload("c", agg_c.host_compress(chunk))
    with pytest.raises(ValueError, match="tier template"):
        eng.submit_payload("c", {"v": np.zeros(3, np.int64),
                                 "r": np.zeros(3, np.int32)})
    # a NESTED payload (e.g. a fused multi-query codec dict) must fail
    # at the submitter, not poison the template as a 0-d object array
    with pytest.raises(ValueError, match="FLAT dict"):
        eng.submit_payload("c", {"cc": {"v": np.zeros(2, np.int32)}})
    # out-of-range ids raise at the submitter (payload_to_chunk
    # parity) — on device they would silently drop/clamp
    with pytest.raises(ValueError, match="out of range"):
        eng.submit_payload("c", {"v": np.asarray([N_V + 3], np.int32),
                                 "r": np.asarray([0], np.int32)})
    # one tenant's oversized payload must not inflate every lane's
    # padded bucket: variable keys are bounded by 2 x chunk_capacity
    big = np.arange(2 * CHUNK + 1, dtype=np.int32) % N_V
    with pytest.raises(ValueError, match="tier bound"):
        eng.submit_payload("c", {"v": big, "r": np.zeros_like(big)})
    # a compressed tier needs a plan with fold_compressed
    with pytest.raises(ValueError, match="fold_compressed"):
        eng.add_tier("bad", _cc_plan(), cap, compressed=True)
    # ... and host_compress (masked lanes pad with the codec identity
    # payload — a missing one must fail at REGISTRATION, not at the
    # first dispatch with a drained lane)
    import dataclasses

    no_hc = dataclasses.replace(
        agg_c, host_compress=None, name="no-host-compress",
    )
    with pytest.raises(ValueError, match="host_compress"):
        eng.add_tier("bad2", no_hc, cap, compressed=True)


@pytest.mark.ingest
def test_tenant_router_routes_compressed_streams():
    """Wire leg end to end: clients compress BEFORE send
    (DATA_COMPRESSED + tenant tag), the router submits payloads
    straight into the compressed tier, and the folded labels match the
    single-stream oracle — with zero ingest-side compress work."""
    from gelly_tpu.ingest import IngestClient, IngestServer, TenantRouter

    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16, compressed=True,
                              codec="sparse")
    eng = MultiTenantEngine(merge_every=1).start()
    router = TenantRouter(eng, "small", vertex_capacity=N_V)
    eng.add_tier("small", agg, cap, compressed=True)
    edges = {
        t: np.random.default_rng(200 + t).integers(0, N_V, (64, 2))
        for t in (3, 4)
    }
    from gelly_tpu.core.chunk import make_chunk

    def payloads_for(t):
        out = []
        for i in range(0, 64, 16):
            s = edges[t][i:i + 16, 0].astype(np.int64)
            d = edges[t][i:i + 16, 1].astype(np.int64)
            c = make_chunk(s.astype(np.int32), d.astype(np.int32),
                           raw_src=s, raw_dst=d, capacity=16,
                           device=False)
            p = dict(agg.host_compress(c))
            p["tenant"] = np.array([t], np.int64)
            out.append(p)
        return out

    servers, clients = [], []
    try:
        for t in (3, 4):
            s = IngestServer(port=0).start()
            router.attach(s)
            c = IngestClient("127.0.0.1", s.port).connect()
            servers.append(s)
            clients.append((t, c))
        for t, c in clients:
            for p in payloads_for(t):
                c.send_compressed(p)
            c.flush()
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if (eng.queue_depth() == 0
                        and eng.position(3) >= 4
                        and eng.position(4) >= 4):
                    break
            except KeyError:
                pass  # auto-admission not seen yet
            time.sleep(0.05)
        for t in (3, 4):
            eng.finish(t)
        deadline = time.time() + 10
        while time.time() < deadline and any(
            eng.snapshot_window(t) == 0 for t in (3, 4)
        ):
            time.sleep(0.05)
        got = {t: eng.labels(t) for t in (3, 4)}
    finally:
        eng.stop()
        for s in servers:
            s.stop()
        router.stop()
    raw_plan = _cc_plan()
    for t in (3, 4):
        st = edge_stream_from_edges(
            [(int(a), int(b)) for a, b in edges[t]],
            vertex_capacity=N_V, chunk_size=16,
            table=IdentityVertexTable(N_V),
        )
        want = np.asarray(st.aggregate(raw_plan, merge_every=1).result())
        assert got[t].tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# wire routing (ingest front end)


@pytest.mark.ingest
def test_tenant_router_routes_n_client_streams():
    from gelly_tpu.ingest import IngestClient, IngestServer, TenantRouter
    from gelly_tpu.ingest.client import edge_payload

    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
    eng = MultiTenantEngine(merge_every=1).start()
    router = TenantRouter(eng, "small", vertex_capacity=N_V)
    eng.add_tier("small", agg, cap)
    edges = {
        t: np.random.default_rng(t).integers(0, N_V, (64, 2))
        for t in (7, 9)
    }
    servers, clients = [], []
    try:
        for t in (7, 9):
            s = IngestServer(port=0).start()
            router.attach(s)
            c = IngestClient("127.0.0.1", s.port).connect()
            servers.append(s)
            clients.append((t, c))
        for t, c in clients:
            for i in range(0, 64, 16):
                p = edge_payload(edges[t][i:i + 16, 0],
                                 edges[t][i:i + 16, 1])
                p["tenant"] = np.array([t], np.int64)
                c.send(p)
            c.flush()
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if (eng.queue_depth() == 0
                        and eng.position(7) >= 4
                        and eng.position(9) >= 4):
                    break
            except KeyError:
                pass  # auto-admission not seen yet
            time.sleep(0.05)
        for t in (7, 9):
            eng.finish(t)
        deadline = time.time() + 10
        while time.time() < deadline and any(
            eng.snapshot_window(t) == 0 for t in (7, 9)
        ):
            time.sleep(0.05)
        got = {t: eng.labels(t) for t in (7, 9)}
    finally:
        eng.stop()
        for s in servers:
            s.stop()
        router.stop()
    for t in (7, 9):
        st = edge_stream_from_edges(
            [(int(a), int(b)) for a, b in edges[t]],
            vertex_capacity=N_V, chunk_size=16,
            table=IdentityVertexTable(N_V),
        )
        want = np.asarray(st.aggregate(agg, merge_every=1).result())
        assert got[t].tobytes() == want.tobytes()


@pytest.mark.ingest
def test_tenant_router_unroutable_payloads_counted():
    from gelly_tpu.ingest import IngestClient, IngestServer, TenantRouter
    from gelly_tpu.ingest.client import edge_payload

    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1).start()
        eng.add_tier("small", agg, cap)
        router = TenantRouter(eng, "small", vertex_capacity=N_V,
                              auto_admit=False)
        s = IngestServer(port=0).start()
        router.attach(s)
        try:
            c = IngestClient("127.0.0.1", s.port).connect()
            p = edge_payload(np.array([1, 2]), np.array([3, 4]))
            p["tenant"] = np.array([42], np.int64)  # never admitted
            c.send(p)
            c.flush()
            deadline = time.time() + 10
            while (time.time() < deadline and
                   bus.counters.get("ingest.chunks_unroutable", 0) < 1):
                time.sleep(0.02)
            assert bus.counters["ingest.chunks_unroutable"] >= 1
        finally:
            eng.stop()
            s.stop()
            router.stop()


# --------------------------------------------------------------------- #
# idle-lane reclamation (lane widths previously only grew)


def _feed_blocking(eng, tid, it, n):
    """Submit up to n chunks, blocking on a small queue bound so chunks
    are never dropped on the floor."""
    fed = 0
    deadline = time.time() + 60
    while fed < n and time.time() < deadline:
        c = next(it, None)
        if c is None:
            break
        while eng.queue_depth(tid) >= 2 and time.time() < deadline:
            time.sleep(0.002)
        eng.submit(tid, c)
        fed += 1
    return fed


def test_idle_lane_reclamation_halves_width(tmp_path):
    """High-water live count below width/2 for K consecutive windows
    halves the tier stack: evicted (done) tenants' rows are snapshotted
    (queries keep answering, final checkpoint durable) and live tenants
    compact into the low lanes; later admissions reuse them."""
    cc = _cc_plan()
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1, reclaim_after=2,
                                checkpoint_dir=str(tmp_path))
        eng.add_tier("cc", cc, CHUNK)
        for i in range(6):  # short streams: finish after 3 windows
            eng.admit(i, "cc", chunks=_stream(i))
        eng.admit("a", "cc")
        eng.admit("b", "cc")
        tier = eng._tiers["cc"]
        assert tier.batch.lanes == 8
        eng.start()
        try:
            chunks = {t: list(_stream(900 + ord(t), n_edges=960))
                      for t in ("a", "b")}
            feeds = {t: iter(cs) for t, cs in chunks.items()}
            fed = {"a": 0, "b": 0}
            # Phase 1: 2 live tenants of 8 lanes — high-water 2 < 8/2,
            # so the stack halves to 4 (and stops there: 2*2 < 4 is
            # false, the hysteresis bound).
            deadline = time.time() + 90
            while time.time() < deadline and tier.batch.lanes > 4:
                for t, it in feeds.items():
                    fed[t] += _feed_blocking(eng, t, it, 1)
            assert tier.batch.lanes == 4
            # Phase 2: finish one live tenant; high-water drops to 1 <
            # 4/2 and the stack halves again to the 2-lane pow-2 floor.
            eng.finish("b")
            while time.time() < deadline and tier.batch.lanes > 2:
                fed["a"] += _feed_blocking(eng, "a", feeds["a"], 1)
            assert tier.batch.lanes == 2
            assert eng.stats["reclaims"] >= 2
            assert eng.stats["lanes_reclaimed"] >= 6
            assert bus.counters["tenants.reclaims"] >= 2
            assert bus.counters["tenants.lanes_reclaimed"] >= 6

            # Evicted tenants: queries answer from the parked row,
            # bit-identical to the standalone oracle, with a durable
            # final checkpoint at the evicted position.
            for i in range(6):
                got = eng.labels(i)
                assert got is not None
                want = np.asarray(
                    _stream(i).aggregate(cc, merge_every=1).result()
                )
                assert got.tobytes() == want.tobytes()
                assert eng.snapshot_window(i) > 0
                pos = eng.position(i)
                assert os.path.exists(
                    eng._tenants[i].manager.path_for(pos)
                )

            # A post-reclaim admission reuses the freed lane space.
            lane_c = eng.admit("c", "cc", chunks=_stream(777))
            assert lane_c <= 2
            eng.finish("a")
            deadline = time.time() + 60
            while time.time() < deadline and any(
                not t.done for t in eng._tenants.values()
            ):
                time.sleep(0.01)
        finally:
            eng.stop()
        # Live tenants were remapped mid-serving and the new admission
        # rode the shrunken stack: all still bit-identical to oracles
        # over exactly the chunks they folded.
        from gelly_tpu.engine.aggregation import run_aggregation

        for tid in ("a", "b"):
            assert eng.position(tid) == fed[tid]
            want = np.asarray(run_aggregation(
                cc, chunks[tid][: fed[tid]], merge_every=1,
                ingest_workers=0, prefetch_depth=0, h2d_depth=0,
            ).result())
            got = eng.labels(tid)
            assert got is not None
            assert got.tobytes() == want.tobytes(), tid
        want_c = np.asarray(
            _stream(777).aggregate(cc, merge_every=1).result()
        )
        got_c = eng.labels("c")
        assert got_c is not None and got_c.tobytes() == want_c.tobytes()


def test_reclamation_respects_min_lanes_and_stays_off_by_default():
    """min_lanes floors the shrink target, and an engine without
    reclaim_after never reclaims no matter how idle the tier goes."""
    cc = _cc_plan()
    # Default: off. Drain a tier down to one live tenant; width stays.
    eng = MultiTenantEngine(merge_every=1)
    eng.add_tier("cc", cc, CHUNK)
    for i in range(4):
        eng.admit(i, "cc", chunks=_stream(i))
    eng.drain()
    assert eng._tiers["cc"].batch.lanes == 4
    assert eng.stats["reclaims"] == 0

    # min_lanes=4 floors the target: 1 live tenant of 4 lanes never
    # shrinks below the floor (and so never reclaims at all here).
    eng2 = MultiTenantEngine(merge_every=1, reclaim_after=1)
    eng2.add_tier("cc", _cc_plan(), CHUNK, min_lanes=4)
    for i in range(3):
        eng2.admit(i, "cc", chunks=_stream(i))
    eng2.admit("live", "cc")
    eng2.start()
    try:
        it = iter(list(_stream(321, n_edges=320)))
        _feed_blocking(eng2, "live", it, 10)
        time.sleep(0.3)  # several windows' worth of close cadence
        assert eng2._tiers["cc"].batch.lanes == 4
        assert eng2.stats["reclaims"] == 0
        eng2.finish("live")
        deadline = time.time() + 30
        while time.time() < deadline and any(
            not t.done for t in eng2._tenants.values()
        ):
            time.sleep(0.01)
    finally:
        eng2.stop()


def test_reclaim_after_validation():
    with pytest.raises(ValueError, match="reclaim_after"):
        MultiTenantEngine(reclaim_after=0)


def test_reclamation_defers_while_a_tenant_is_half_admitted():
    """admit() publishes (lane, resume state, readiness) in stages; a
    reclaim interleaving with it would remap or drop the lane the
    admission still holds. The reclaim body therefore DEFERS whenever
    any lane-holding tenant is not yet ready — and proceeds once the
    admission completes."""
    cc = _cc_plan()
    with obs_bus.scope():
        eng = MultiTenantEngine(merge_every=1, reclaim_after=1)
        eng.add_tier("cc", cc, CHUNK)
        for i in range(4):
            eng.admit(i, "cc", chunks=_stream(i))
        eng.admit("live", "cc")
        for c in _stream(55, n_edges=64):
            eng.submit("live", c)
        eng.finish("live")
        eng.drain()  # everyone done; width 8 (5 admits)
        tier = eng._tiers["cc"]
        width0 = tier.batch.lanes
        # Simulate an in-flight admission: insert a lane-holding tenant
        # that admit() has not yet marked ready, then force the reclaim
        # conditions — the body must refuse to shrink.
        from gelly_tpu.engine.tenants import _Tenant

        half = _Tenant("half", "cc", width0 - 1)
        with eng._lock:
            eng._tenants["half"] = half
        tier.low_windows = 10
        tier.hw_active = 0
        eng._maybe_reclaim(tier, obs_bus.get_bus(), None)
        assert tier.batch.lanes == width0
        assert eng.stats["reclaims"] == 0
        # Admission completes: the same conditions now reclaim.
        with eng._lock:
            half.ready = True
            half.done = True  # finished instantly; lane is evictable
        tier.low_windows = 10
        eng._maybe_reclaim(tier, obs_bus.get_bus(), None)
        assert tier.batch.lanes < width0
        assert eng.stats["reclaims"] == 1
