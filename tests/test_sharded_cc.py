"""Slot-sharded CC summaries (VERDICT r3 item 2): the summary state itself
is vertex-striped across the mesh — per-device memory capacity/S — with
pair routing over the keyed exchange and a bounded hook/flatten loop.

Parity oracle: the replicated plans' label semantics (canonical min slot,
-1 unseen), asserted exactly against cc_labels_numpy on the 8-virtual-
device CPU mesh.
"""

import numpy as np
import pytest

from gelly_tpu.library.connected_components import cc_labels_numpy
from gelly_tpu.parallel import mesh as mesh_lib
from gelly_tpu.parallel.sharded_cc import ShardedCC

N_V = 512


def _pairs(n_e, seed, n_v=N_V):
    rng = np.random.default_rng(seed)
    a = (rng.zipf(1.4, n_e) % n_v).astype(np.int32)
    b = (rng.zipf(1.4, n_e) % n_v).astype(np.int32)
    return a, b


def test_sharded_cc_parity_single_fold():
    a, b = _pairs(600, seed=1)
    cc = ShardedCC(N_V)  # all 8 virtual devices
    cc.fold(a, b)
    labels = cc.labels()
    oracle = cc_labels_numpy(a, b, None, N_V)
    assert np.array_equal(labels, oracle)
    assert cc.stats["dropped"] == 0


def test_sharded_cc_parity_many_folds():
    # Sequential dispatches over one sharded forest (the streaming shape):
    # intermediate labels() calls flatten mid-stream and folding must
    # continue correctly afterwards.
    cc = ShardedCC(N_V)
    alla, allb = [], []
    for i, seed in enumerate([3, 4, 5, 6]):
        a, b = _pairs(300, seed=seed)
        alla.append(a)
        allb.append(b)
        cc.fold(a, b)
        if i == 1:
            mid = cc.labels()
            mid_oracle = cc_labels_numpy(
                np.concatenate(alla), np.concatenate(allb), None, N_V
            )
            assert np.array_equal(mid, mid_oracle)
    labels = cc.labels()
    oracle = cc_labels_numpy(
        np.concatenate(alla), np.concatenate(allb), None, N_V
    )
    assert np.array_equal(labels, oracle)


def test_sharded_cc_incremental_emission_every_window():
    """The incremental labels() (dirty-delta resolution against the host
    root cache, VERDICT r4 item 3) must match the oracle at EVERY window
    close — including windows that lower an old component's canonical
    root (the whole component's labels must drop through the one-gather
    delta map), and empty windows (no dirty entries)."""
    cc = ShardedCC(N_V)
    alla, allb = [], []
    rng = np.random.default_rng(40)
    for w in range(6):
        if w == 3:
            # Deliberately hook an old component to a LOWER root: vertex 0
            # joins whatever component vertex N_V-1 is in.
            a = np.array([0], np.int64)
            b = np.array([N_V - 1], np.int64)
        elif w == 4:
            a = np.empty(0, np.int64)  # empty window: no dirty entries
            b = np.empty(0, np.int64)
        else:
            a = rng.integers(N_V // 2, N_V, 200)
            b = rng.integers(N_V // 2, N_V, 200)
        alla.append(a)
        allb.append(b)
        if a.size:
            cc.fold(a, b)
        labels = cc.labels()
        oracle = cc_labels_numpy(
            np.concatenate(alla).astype(np.int64),
            np.concatenate(allb).astype(np.int64), None, N_V,
        )
        assert np.array_equal(labels, oracle), f"window {w}"
    assert cc.stats["dropped"] == 0


def test_sharded_cc_sparse_delta_pull_parity():
    """The sparse ``_pull_delta`` emission path (device-side dirty
    compaction + global-slot reconstruction) engages only when the padded
    buckets are cheaper than a full pull — ``S*bucket*2 < capacity`` —
    which N_V=512 can never reach (8*64*2 >= 512 always takes the dense
    fallback). Run at 2^14 with small folds so every emission after the
    first crosses the link through the compacted rows, and parity must
    hold at every window close including a root-lowering hook."""
    n = 1 << 14
    cc = ShardedCC(n)
    rng = np.random.default_rng(77)
    alla, allb = [], []
    for w in range(5):
        if w == 3:
            # Hook an old component to a LOWER root mid-stream: the
            # sparse delta map must drop the whole component's labels.
            a = np.array([1], np.int64)
            b = np.array([n - 1], np.int64)
        else:
            a = rng.integers(n // 2, n, 200)
            b = rng.integers(n // 2, n, 200)
        alla.append(a)
        allb.append(b)
        cc.fold(a, b)
        labels = cc.labels()
        oracle = cc_labels_numpy(
            np.concatenate(alla).astype(np.int64),
            np.concatenate(allb).astype(np.int64), None, n,
        )
        assert np.array_equal(labels, oracle), f"window {w}"
    # The sparse branch compiled at least one bucketed pull — the dense
    # fallback never touches ``_pull_fns``.
    assert cc._pull_fns, "sparse _pull_delta path never engaged"
    assert cc.stats["dropped"] == 0


def test_sharded_cc_valid_mask_and_padding():
    a = np.array([0, 9, 17, 33], np.int32)
    b = np.array([9, 17, 99, 207], np.int32)
    ok = np.array([True, True, False, True])
    cc = ShardedCC(N_V)
    cc.fold(a, b, ok)  # 4 pairs pad unevenly across 8 shards
    labels = cc.labels()
    oracle = cc_labels_numpy(a[ok], b[ok], None, N_V)
    assert np.array_equal(labels, oracle)


def test_sharded_cc_state_is_striped():
    # The VERDICT criterion: per-device state is capacity/S, not capacity.
    cc = ShardedCC(N_V)
    S = cc.S
    assert S == 8
    assert cc.parent.shape == (S, N_V // S)
    assert cc.per_device_state_bytes() == (N_V // S) * 5
    # Each row of the device-sharded parent is one device's stripe.
    shards = cc.parent.addressable_shards
    assert len(shards) == S
    assert all(s.data.shape == (1, N_V // S) for s in shards)


def test_sharded_cc_capacity_not_divisible_raises():
    with pytest.raises(ValueError, match="not divisible"):
        ShardedCC(N_V + 3)


def test_sharded_cc_small_mesh():
    a, b = _pairs(200, seed=9)
    cc = ShardedCC(N_V, mesh=mesh_lib.make_mesh(2))
    cc.fold(a, b)
    assert np.array_equal(cc.labels(), cc_labels_numpy(a, b, None, N_V))
