"""Multi-tenant exactly-once wire: pre-shared-key auth, per-tenant
sequence spaces, WELCOME park/pause/shed state, tenant-scoped
PAUSE/RESUME, typed NACK shed, tenant-mode CRC resync, checkpoint-gated
per-tenant acks, and the SIGKILL crash child proving no acked chunk is
ever double-folded across a server restart.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.engine.checkpoint import load_checkpoint
from gelly_tpu.engine.tenants import MultiTenantEngine
from gelly_tpu.ingest import IngestClient, IngestServer, TenantRouter
from gelly_tpu.ingest import wire
from gelly_tpu.ingest.client import IngestError, edge_payload
from gelly_tpu.ingest.server import payload_to_chunk
from gelly_tpu.library.connected_components import cc_tenant_tier
from gelly_tpu.obs import bus as obs_bus

pytestmark = pytest.mark.ingest

N_V = 128
CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_qos_crash_child.py")


def _drain_frames(srv, out):
    """Background consumer keeping (seq, payload, compressed) triples."""
    def run():
        for item in srv.frames():
            out.append(item)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _wait(pred, timeout=20.0, step=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _read_frame(sock):
    return wire.read_frame(sock.recv)


# --------------------------------------------------------------------- #
# pre-shared-key HELLO auth


def test_auth_handshake_accepts_matching_token():
    with obs_bus.scope() as bus:
        with IngestServer(auth_token="sesame") as srv:
            out = []
            _drain_frames(srv, out)
            with IngestClient("127.0.0.1", srv.port,
                              auth_token="sesame") as cli:
                for i in range(3):
                    cli.send(edge_payload([i, i + 1], [i + 2, i + 3]))
                cli.flush()
            assert _wait(lambda: len(out) == 3)
        counters = bus.snapshot()["counters"]
        assert counters.get("ingest.auth_challenges") == 1
        assert "ingest.auth_failures" not in counters


def test_auth_missing_token_raises_before_streaming():
    with IngestServer(auth_token="sesame") as srv:
        cli = IngestClient("127.0.0.1", srv.port)  # no token
        with pytest.raises(IngestError, match="pre-shared auth token"):
            cli.connect()


def test_auth_wrong_token_gets_typed_auth_fail():
    with obs_bus.scope() as bus:
        with IngestServer(auth_token="sesame") as srv:
            cli = IngestClient("127.0.0.1", srv.port, auth_token="wrong")
            with pytest.raises(IngestError,
                               match="authentication failed"):
                cli.connect()
        counters = bus.snapshot()["counters"]
        assert counters.get("ingest.auth_failures") == 1
        assert counters.get("ingest.auth_challenges") == 1


def test_auth_refuses_data_before_handshake():
    """Nothing but the handshake crosses an unauthed connection: a raw
    DATA frame is answered with AUTH_FAIL and the connection closes."""
    with obs_bus.scope() as bus:
        with IngestServer(auth_token="sesame") as srv:
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.settimeout(5)
            try:
                raw.sendall(wire.pack_frame(
                    wire.DATA, 0,
                    wire.pack_payload(edge_payload([1], [2]))))
                ftype, _seq, _payload = _read_frame(raw)
                assert ftype == wire.AUTH_FAIL
                # Terminal: the server closes after AUTH_FAIL.
                assert _read_frame(raw)[0] == wire.BYE  # clean EOF
            finally:
                raw.close()
        assert bus.snapshot()["counters"].get(
            "ingest.auth_failures") == 1


# --------------------------------------------------------------------- #
# per-tenant sequence spaces


def test_tenant_streams_have_distinct_seq_spaces():
    with IngestServer(tenant_streams=True) as srv:
        out = []
        _drain_frames(srv, out)
        cli = IngestClient("127.0.0.1", srv.port,
                           tenant_streams=True).connect()
        try:
            for i in range(4):
                cli.send(edge_payload([i], [i + 1]), tenant=7)
                if i < 2:
                    cli.send(edge_payload([i], [i + 2]), tenant=9)
            cli.flush()
            # Per-tenant acks: each space acknowledges its OWN count.
            assert cli.acked_for(7) == 4
            assert cli.acked_for(9) == 2
            assert _wait(lambda: len(out) == 6)
            seqs = {
                (int(np.asarray(p["tenant"]).reshape(-1)[0]), s)
                for s, p, _ in out
            }
            # Both spaces start at 0 — they are DISTINCT, not one
            # interleaved counter.
            assert seqs == {(7, 0), (7, 1), (7, 2), (7, 3),
                            (9, 0), (9, 1)}
        finally:
            cli.close(flush_timeout=None)


# --------------------------------------------------------------------- #
# WELCOME carries park/pause/shed state (reconnect regression)


def test_welcome_carries_tenant_hold_and_release():
    """Regression, both directions: a hold placed while NO client is
    connected lands via WELCOME (the reconnecting client holds
    immediately); a release while disconnected also lands (the client
    does not stay stuck on stale hold state)."""
    with IngestServer(tenant_streams=True) as srv:
        srv.pause_tenant(3)  # no connection yet: state only
        cli = IngestClient("127.0.0.1", srv.port, tenant_streams=True,
                           send_pause_timeout=10).connect()
        try:
            assert cli.tenant_paused(3)
            assert not cli.tenant_paused(4)
            # The un-held tenant flows.
            cli.send(edge_payload([1], [2]), tenant=4)
            cli.flush()
            # The held tenant's send blocks until the policy release.
            done = threading.Event()

            def held_send():
                cli.send(edge_payload([5], [6]), tenant=3)
                done.set()

            t = threading.Thread(target=held_send, daemon=True)
            t.start()
            time.sleep(0.2)
            assert not done.is_set()
            srv.resume_tenant(3)
            assert done.wait(5)
            cli.flush()
            # Direction two: release while DISCONNECTED.
            srv.pause_tenant(3)
            assert _wait(lambda: cli.tenant_paused(3))
            cli.close(flush_timeout=None)
            srv.resume_tenant(3)  # lands in no socket — state only
            cli.connect()
            assert not cli.tenant_paused(3)
            cli.send(edge_payload([7], [8]), tenant=3)
            cli.flush()
        finally:
            cli.close(flush_timeout=None)


def test_welcome_carries_legacy_pause_bit():
    """Legacy single-stream server: a policy hold (one tenant per
    server) pauses the WHOLE wire, and WELCOME carries the bit so a
    reconnecting client holds immediately."""
    with IngestServer() as srv:
        srv.pause_tenant(0)
        cli = IngestClient("127.0.0.1", srv.port).connect()
        try:
            assert cli.paused
            srv.resume_tenant(0)
            assert _wait(lambda: not cli.paused)
            cli.send(edge_payload([1], [2]))
            cli.flush()
        finally:
            cli.close(flush_timeout=None)


# --------------------------------------------------------------------- #
# typed NACK shed


def test_shed_tenant_nacks_and_closes_stream():
    with obs_bus.scope() as bus:
        with IngestServer(tenant_streams=True) as srv:
            out = []
            _drain_frames(srv, out)
            cli = IngestClient("127.0.0.1", srv.port,
                               tenant_streams=True).connect()
            cli.send(edge_payload([1], [2]), tenant=5)
            cli.send(edge_payload([1], [2]), tenant=6)
            cli.flush()
            srv.shed_tenant(5, reason="overload")
            assert _wait(lambda: 5 in cli.shed_tenants)
            assert cli.shed_tenants[5] == "overload"
            with pytest.raises(IngestError, match="shed"):
                cli.send(edge_payload([3], [4]), tenant=5)
            # The OTHER tenant's stream is untouched.
            cli.send(edge_payload([3], [4]), tenant=6)
            cli.flush()
            assert cli.acked_for(6) == 2
            cli.close(flush_timeout=None)
            # A late frame for the shed tenant (a client that never
            # heard the NACK) is refused with a typed NACK carrying the
            # durable position — raw socket, so the frame really
            # arrives.
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.settimeout(5)
            try:
                raw.sendall(wire.pack_frame(wire.HELLO, 0))
                ftype, _seq, wbody = _read_frame(raw)
                assert ftype == wire.WELCOME
                info = wire.unpack_json(wbody)
                assert info["shed_tenants"] == [5]
                p = edge_payload([9], [10])
                p["tenant"] = np.asarray([5], dtype=np.int64)
                raw.sendall(wire.pack_frame(
                    wire.DATA, 1, wire.pack_payload(p)))
                ftype, seq, body = _read_frame(raw)
                assert ftype == wire.NACK
                # The NACK's seq is the DURABLE position — auto_ack
                # acks are not durability claims, so it stays 0 here.
                assert seq == 0
                env = wire.unpack_json(body)
                assert env == {"reason": "overload", "tenant": 5}
            finally:
                raw.close()
        counters = bus.snapshot()["counters"]
        assert counters.get("ingest.nacks_sent", 0) >= 2
        assert counters.get("ingest.nacks_received") == 1
        assert counters.get("ingest.frames_shed") == 1


# --------------------------------------------------------------------- #
# tenant-mode CRC resync


def test_tenant_mode_crc_corruption_resyncs_and_completes():
    """A corrupt frame in tenant_streams mode cannot name its stream
    (the tenant id lives in the unverifiable payload): the server asks
    for a full resync and the client retransmits every unacked frame —
    duplicates drop, the stream completes, labels bit-identical."""
    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
    edges = np.random.default_rng(41).integers(0, N_V, (64, 2))
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1).start()
        router = TenantRouter(eng, "small", vertex_capacity=N_V)
        eng.add_tier("small", agg, cap)
        srv = IngestServer(tenant_streams=True).start()
        router.attach(srv)
        cli = IngestClient("127.0.0.1", srv.port,
                           tenant_streams=True).connect()
        try:
            orig = cli._raw_send
            left = [1]

            def corrupting(frame):
                if left[0] and len(frame) > 200:  # only DATA is this big
                    left[0] -= 1
                    frame = frame[:-1] + bytes([frame[-1] ^ 0xFF])
                orig(frame)

            cli._raw_send = corrupting
            for i in range(0, 64, 16):
                cli.send(edge_payload(edges[i:i + 16, 0],
                                      edges[i:i + 16, 1]), tenant=3)
            cli.flush(timeout=30)
            assert left[0] == 0  # the corruption really happened
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    if eng.queue_depth() == 0 and eng.position(3) >= 4:
                        break
                except KeyError:
                    pass  # auto-admission not seen yet
                time.sleep(0.05)
            eng.finish(3)
            assert _wait(lambda: eng.snapshot_window(3) > 0, timeout=10)
            got = eng.labels(3)
        finally:
            cli.close(flush_timeout=None)
            eng.stop()
            srv.stop()
            router.stop()
        counters = bus.snapshot()["counters"]
        assert counters.get("ingest.frames_rejected", 0) >= 1
        assert counters.get("ingest.frames_resent", 0) >= 1
    st = edge_stream_from_edges(
        [(int(a), int(b)) for a, b in edges], vertex_capacity=N_V,
        chunk_size=16, table=IdentityVertexTable(N_V),
    )
    want = np.asarray(st.aggregate(agg, merge_every=1).result())
    assert got.tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# checkpoint-gated per-tenant acks


def test_checkpoint_gated_acks_flow_through_router(tmp_path):
    """auto_ack=False + checkpoint_acks=True: a tenant's wire ACK fires
    only from the engine's on_durable hook after its CheckpointManager
    rotation — flush() completing IS the durability proof."""
    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
    edges = {
        t: np.random.default_rng(50 + t).integers(0, N_V, (64, 2))
        for t in (1, 2)
    }
    eng = MultiTenantEngine(
        merge_every=1, checkpoint_dir=str(tmp_path), checkpoint_every=1,
    ).start()
    router = TenantRouter(eng, "small", vertex_capacity=N_V,
                          checkpoint_acks=True)
    eng.add_tier("small", agg, cap)
    srv = IngestServer(tenant_streams=True, auto_ack=False).start()
    router.attach(srv)
    cli = IngestClient("127.0.0.1", srv.port,
                       tenant_streams=True).connect()
    try:
        for t in (1, 2):
            for i in range(0, 64, 16):
                cli.send(edge_payload(edges[t][i:i + 16, 0],
                                      edges[t][i:i + 16, 1]), tenant=t)
        cli.flush(timeout=60)  # completes only via checkpoint-gated acks
        for t in (1, 2):
            assert cli.acked_for(t) == 4
            assert eng.position(t) == 4
            assert list(tmp_path.glob(f"t{t}-*.npz"))
        assert cli.unacked_count == 0
    finally:
        cli.close(flush_timeout=None)
        eng.stop()
        srv.stop()
        router.stop()


# --------------------------------------------------------------------- #
# STACKED frames in tenant mode


def test_stacks_are_tenant_scoped_on_the_wire():
    """A coalescing client keys its stack buffers by tenant, so every
    STACKED frame that reaches the server is single-tenant-scoped and
    rides that tenant's OWN sequence space — interleaved sends to two
    tenants never share a frame."""
    with obs_bus.scope() as bus:
        with IngestServer(tenant_streams=True) as srv:
            out = []
            _drain_frames(srv, out)
            cli = IngestClient("127.0.0.1", srv.port,
                               tenant_streams=True, stack=3).connect()
            try:
                for i in range(4):
                    cli.send(edge_payload([i], [i + 1]), tenant=7)
                    if i < 2:
                        cli.send(edge_payload([i], [i + 2]), tenant=9)
                cli.flush()
                # Frame-granular acks still land per tenant space.
                assert cli.acked_for(7) == 4
                assert cli.acked_for(9) == 2
                assert _wait(lambda: len(out) == 6)
                seqs = {
                    (int(np.asarray(p["tenant"]).reshape(-1)[0]), s)
                    for s, p, _ in out
                }
                assert seqs == {(7, 0), (7, 1), (7, 2), (7, 3),
                                (9, 0), (9, 1)}
            finally:
                cli.close(flush_timeout=None)
        counters = bus.snapshot()["counters"]
        # t7: one full stack [0,3) + a K=1 tail (legacy DATA frame);
        # t9: one K=2 tail stack. Stacks never straddled tenants.
        assert counters.get("ingest.frames_stacked") == 2
        assert counters.get("ingest.chunks_unroutable", 0) == 0


def test_mixed_tenant_stack_refused_whole():
    """A hand-crafted stack that straddles tenant ids (or omits one)
    has no single sequence space to land in: the server refuses it
    WHOLE — no partial admission, no seq advance — and counts
    ``chunks_unroutable``. A clean stack then lands at the untouched
    position."""
    def tp(t, v):
        p = edge_payload([v], [v + 1])
        if t is not None:
            p["tenant"] = np.asarray([t], dtype=np.int64)
        return p

    def stack(*payloads):
        return wire.pack_stacked(
            [(wire.pack_payload(p), False) for p in payloads])

    with obs_bus.scope() as bus:
        with IngestServer(tenant_streams=True) as srv:
            out = []
            _drain_frames(srv, out)
            raw = socket.create_connection(("127.0.0.1", srv.port))
            raw.settimeout(5)
            try:
                raw.sendall(wire.pack_frame(wire.HELLO, 0))
                assert _read_frame(raw)[0] == wire.WELCOME
                # Straddling stack: tenants 3 and 4 in one frame.
                raw.sendall(wire.pack_frame(
                    wire.STACKED, 0, stack(tp(3, 1), tp(4, 2))))
                # Tenant-less stack: no sequence space at all.
                raw.sendall(wire.pack_frame(
                    wire.STACKED, 0, stack(tp(None, 3), tp(None, 4))))
                # Both were dropped whole — seq 0 is untouched, so a
                # clean single-tenant stack lands there and is acked at
                # frame granularity.
                raw.sendall(wire.pack_frame(
                    wire.STACKED, 0, stack(tp(3, 5), tp(3, 6))))
                ftype, seq, body = _read_frame(raw)
                assert ftype == wire.ACK
                assert seq == 2
                assert wire.unpack_json(body) == {"tenant": 3}
            finally:
                raw.close()
            assert _wait(lambda: len(out) == 2)
            assert all(
                int(np.asarray(p["tenant"]).reshape(-1)[0]) == 3
                for _s, p, _c in out
            )
        counters = bus.snapshot()["counters"]
        assert counters.get("ingest.chunks_unroutable") == 2
        assert counters.get("ingest.frames_stacked") == 1


def test_stacked_tenant_stream_folds_bit_identical_through_router():
    """Whole stacks ride the TenantRouter as one drain unit each and
    the folded labels are bit-identical to the in-process engine run —
    stacking is invisible to the tenant fold."""
    agg, cap = cc_tenant_tier(N_V, chunk_capacity=16)
    edges = np.random.default_rng(77).integers(0, N_V, (96, 2))
    with obs_bus.scope() as bus:
        eng = MultiTenantEngine(merge_every=1).start()
        router = TenantRouter(eng, "small", vertex_capacity=N_V)
        eng.add_tier("small", agg, cap)
        srv = IngestServer(tenant_streams=True).start()
        router.attach(srv)
        cli = IngestClient("127.0.0.1", srv.port, tenant_streams=True,
                           stack=3).connect()
        try:
            for i in range(0, 96, 16):
                cli.send(edge_payload(edges[i:i + 16, 0],
                                      edges[i:i + 16, 1]), tenant=5)
            cli.flush(timeout=30)

            def folded():
                try:
                    return eng.position(5) >= 6 and eng.queue_depth() == 0
                except KeyError:
                    return False  # auto-admission not seen yet

            assert _wait(folded, timeout=30)
            eng.finish(5)
            assert _wait(lambda: eng.snapshot_window(5) > 0, timeout=10)
            got = eng.labels(5)
        finally:
            cli.close(flush_timeout=None)
            eng.stop()
            srv.stop()
            router.stop()
        counters = bus.snapshot()["counters"]
        # 6 chunks coalesced into two stacks of 3 — two frames, two
        # router drain units, zero rejects.
        assert counters.get("ingest.frames_stacked") == 2
        assert counters.get("ingest.chunks_enqueued") == 6
        assert counters.get("ingest.frames_rejected", 0) == 0
    st = edge_stream_from_edges(
        [(int(a), int(b)) for a, b in edges], vertex_capacity=N_V,
        chunk_size=16, table=IdentityVertexTable(N_V),
    )
    want = np.asarray(st.aggregate(agg, merge_every=1).result())
    assert got.tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# SIGKILL: the multi-tenant exactly-once wire


def _spawn_child(ckpt, port_file, out, total, framing="plain"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(port_file), str(out),
         str(total), framing],
        env=env,
    )


def _wait_port(port_file, proc, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server child exited rc={proc.returncode} before "
                "publishing its port"
            )
        if os.path.exists(port_file):
            return int(open(port_file).read())
        time.sleep(0.02)
    raise AssertionError("server child never published its port")


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.tenants
@pytest.mark.parametrize("stack", [1, 3])
def test_sigkilled_multitenant_server_resumes_exactly_once(
        tmp_path, stack):
    """Three tenants, distinct seq spaces, one tenant_streams server
    with checkpoint-gated acks, SIGKILLed mid-stream: the restarted
    incarnation re-welcomes every tenant at its durable position and
    final degree vectors (non-idempotent counters) are bit-identical to
    an uninterrupted in-process run.

    ``stack=3`` reruns it with a coalescing client: per-tenant stacks,
    checkpoint-gated acks landing MID-frame, and covering-frame
    redelivery across the restart — stacking must be invisible to the
    multi-tenant exactly-once contract."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _qos_crash_child as child

    from gelly_tpu.library.degrees import degree_aggregate

    TIDS = (0, 1, 2)
    total = 10
    edges = {
        t: np.random.default_rng(300 + t).integers(
            0, child.N_V, (total * child.CHUNK, 2))
        for t in TIDS
    }

    def mk(t, i):
        lo, hi = i * child.CHUNK, (i + 1) * child.CHUNK
        return edge_payload(edges[t][lo:hi, 0], edges[t][lo:hi, 1])

    # Golden: the same chunks through the same engine, in-process,
    # uninterrupted (degrees are additive, so cadence is immaterial —
    # but a double- or dropped-fold changes the counts).
    agg = degree_aggregate(child.N_V, ingest_combine=False)
    geng = MultiTenantEngine(merge_every=2)
    geng.add_tier("deg", agg, child.CHUNK)
    for t in TIDS:
        geng.admit(t, "deg")
    for i in range(total):
        for t in TIDS:
            geng.submit(t, payload_to_chunk(mk(t, i), child.CHUNK,
                                            child.N_V))
    for t in TIDS:
        geng.finish(t)
    golden = {t: np.asarray(v) for t, v in geng.drain().items()}

    framing = "stacked" if stack > 1 else "plain"
    ckpt = tmp_path / "ckpt"
    port_file = str(tmp_path / "port")
    out = str(tmp_path / "final.npz")
    p1 = _spawn_child(ckpt, port_file, out, total, framing)
    port = _wait_port(port_file, p1)
    cli = IngestClient("127.0.0.1", port, tenant_streams=True,
                       send_pause_timeout=60, stack=stack)
    cli.connect()

    def sender():
        try:
            for i in range(total):
                for t in TIDS:
                    cli.send(mk(t, i), tenant=t)
                time.sleep(0.03)
        except IngestError:
            return  # server died mid-send; the suffix resends below

    st = threading.Thread(target=sender, daemon=True)
    st.start()

    # Kill once every tenant has a durable checkpoint and acks flowed.
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if (all(list(ckpt.glob(f"t{t}-*.npz")) for t in TIDS)
                and all(cli.acked_for(t) >= 2 for t in TIDS)):
            break
        time.sleep(0.02)
    else:
        pytest.fail("no per-tenant checkpoints/acks before the deadline")
    acked_before = {t: cli.acked_for(t) for t in TIDS}
    os.kill(p1.pid, signal.SIGKILL)
    assert p1.wait(timeout=60) == -signal.SIGKILL
    assert not os.path.exists(out)  # died mid-stream
    st.join(timeout=60)

    # The client's per-stream counters are the authoritative record of
    # what was buffered (a send that died mid-call still buffered its
    # frame); everything buffered replays on reconnect, everything
    # beyond it is re-sent below.
    with cli._lock:
        buffered = {t: cli._next.get(t, 0) for t in TIDS}

    os.unlink(port_file)
    p2 = _spawn_child(ckpt, port_file, out, total, framing)
    cli.port = _wait_port(port_file, p2)
    deadline = time.monotonic() + 60
    while True:
        try:
            cli.reconnect()
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    for t in TIDS:  # acked work never rewinds
        assert cli.acked_for(t) >= acked_before[t]
    for t in TIDS:
        for i in range(buffered[t], total):
            cli.send(mk(t, i), tenant=t)
    cli.flush(timeout=180)
    cli.close()
    assert p2.wait(timeout=300) == 0

    final, pos, _ = load_checkpoint(
        out, like=[np.zeros_like(golden[t]) for t in TIDS])
    assert pos == total * len(TIDS)
    for t in TIDS:
        assert final[t].dtype == golden[t].dtype
        assert final[t].tobytes() == golden[t].tobytes()
