"""Spanner property tests (set-level, as the reference's unit test does
scenario-wise — T/util/AdjacencyListGraphTest.java:57-87; exact edge parity
is order-dependent by design)."""

import itertools

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.library.spanner import spanner, spanner_edges
from gelly_tpu.parallel import mesh as mesh_lib


def bfs_dist(adj: dict, a: int, b: int) -> float:
    if a == b:
        return 0
    frontier, seen, d = {a}, {a}, 0
    while frontier:
        d += 1
        frontier = {n for f in frontier for n in adj.get(f, ())} - seen
        if b in frontier:
            return d
        seen |= frontier
    return float("inf")


def check_spanner_properties(edges, got, k):
    eset = {frozenset(e) for e in edges}
    # 1. spanner edges are input edges
    for e in got:
        assert frozenset(e) in eset, e
    # 2. every input edge's endpoints within k hops in the spanner
    adj: dict = {}
    for a, b in got:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    for a, b in edges:
        assert bfs_dist(adj, a, b) <= k, (a, b)


@pytest.mark.parametrize("k", [2, 3])
def test_spanner_properties_random_graph(k):
    rng = np.random.default_rng(9)
    verts = list(range(24))
    edges = list({(int(a), int(b))
                  for a, b in rng.integers(0, 24, (80, 2)) if a != b})
    s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=8)
    agg = spanner(32, k)
    summary = s.aggregate(agg, merge_every=2).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, k)
    assert len(got) <= len(edges)


def test_spanner_keeps_tree_edges():
    # A tree has no redundant edges: the spanner must keep all of them.
    edges = [(i, i + 1) for i in range(10)] + [(3, 20), (20, 21)]
    s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=4)
    summary = s.aggregate(spanner(32, 3), merge_every=1).result()
    got = spanner_edges(summary, s.ctx)
    assert {frozenset(e) for e in got} == {frozenset(e) for e in edges}


def test_spanner_prunes_dense_clique():
    # K8 with k=2: once a hub path exists, most edges are within 2 hops.
    edges = list(itertools.combinations(range(8), 2))
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=4)
    summary = s.aggregate(spanner(16, 2), merge_every=1).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, 2)
    assert len(got) < len(edges)  # must prune something in a clique


def test_spanner_multi_shard_merge(devices):
    m = mesh_lib.make_mesh(8)
    rng = np.random.default_rng(4)
    edges = list({(int(a), int(b))
                  for a, b in rng.integers(0, 16, (60, 2)) if a != b})
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=8)
    summary = s.aggregate(spanner(16, 2), mesh=m, merge_every=2).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, 2)


def test_spanner_overflow_flag():
    edges = [(i, i + 1) for i in range(10)]
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=4)
    summary = s.aggregate(spanner(16, 2, max_edges=4), merge_every=1).result()
    with pytest.raises(RuntimeError, match="overflow"):
        spanner_edges(summary, s.ctx)


@pytest.mark.slow  # tier-1 budget: CI heavy lane
def test_sparse_spanner_matches_dense_when_unconstrained():
    # With generous degree/frontier caps the sparse gate sees the same
    # reachability as the dense one => identical accepted edge lists.
    from gelly_tpu.library.spanner import spanner, spanner_edges

    rng = np.random.default_rng(4)
    n_v = 64
    edges = list(zip(rng.integers(0, n_v, 200).tolist(),
                     rng.integers(0, n_v, 200).tolist()))

    def run(**kw):
        s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=64)
        summ = s.aggregate(spanner(n_v, 3, **kw), merge_every=8).result()
        return spanner_edges(summ, s.ctx)

    assert run(max_degree=n_v, max_edges=256) == run(max_edges=256)


def test_sparse_spanner_million_vertex_stretch_property():
    # O(N*D) memory at N = 1M; caps degrade conservatively, so the
    # k-stretch property must hold for every input edge regardless.
    from gelly_tpu.library.spanner import spanner, spanner_edges

    n_v = 1 << 20
    k = 3
    rng = np.random.default_rng(5)
    ids = rng.choice(n_v, 60, replace=False).astype(np.int64)
    edges = []
    for i in range(0, 60, 6):  # small cliques spread over the id space
        group = ids[i:i + 6]
        edges += [(int(a), int(b)) for a in group for b in group if a < b]
    rng.shuffle(edges)

    s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=32)
    summ = s.aggregate(
        spanner(n_v, k, max_edges=256, max_degree=8), merge_every=8
    ).result()
    accepted = set(map(tuple, spanner_edges(summ, s.ctx)))
    assert 0 < len(accepted) < len(set(
        (min(a, b), max(a, b)) for a, b in edges
    ))

    # Host BFS stretch check over the spanner for every input edge.
    # Across partition/window merges the gate re-applies to partial
    # spanners (CombineSpanners semantics, Spanner.java:91-116), so the
    # end-to-end guarantee is k per merge level — assert the k^2 bound
    # that one level of merging provides (the reference degrades the same
    # way; its own tests only assert scenario behavior).
    adj: dict[int, set] = {}
    for a, b in accepted:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)

    def within(u, v, hops):
        frontier = {u}
        seen = {u}
        for _ in range(hops):
            if v in frontier:
                return True
            frontier = {w for x in frontier for w in adj.get(x, ())} - seen
            seen |= frontier
        return v in frontier

    for a, b in edges:
        if a != b:
            assert within(a, b, k * k), (a, b)


# ---------------- native host spanner stage ---------------- #


def _toolchain():
    from gelly_tpu.utils import native

    return native.available("spanner")


@pytest.mark.skipif(not _toolchain(), reason="native toolchain unavailable")
def test_host_spanner_matches_dense_device_exactly():
    # Same stream order + same gate + uncapped degree => identical accepted
    # edge list (not just set) between the native host fold and the dense
    # device scan.
    from gelly_tpu.library.spanner import host_spanner

    rng = np.random.default_rng(21)
    n_v = 128
    edges = [(int(a), int(b), 1.0)
             for a, b in rng.integers(0, n_v, (1200, 2))]

    def stream():
        return edge_stream_from_edges(edges, vertex_capacity=n_v,
                                      chunk_size=128)

    s = stream()
    dev = spanner_edges(
        s.aggregate(
            spanner(n_v, 3), mesh=mesh_lib.make_mesh(1),
            merge_every=10 ** 6,
        ).result(),
        s.ctx,
    )
    host = host_spanner(stream(), 3, max_degree=n_v).final_edges()
    assert host == dev


@pytest.mark.skipif(not _toolchain(), reason="native toolchain unavailable")
@pytest.mark.parametrize("k", [2, 4])
def test_host_spanner_properties_at_scale(k):
    # 50k-edge Zipf stream: subset + k-stretch properties, plus the
    # conservative degree-cap accounting (overflows may only ADD edges).
    from gelly_tpu.library.spanner import host_spanner

    rng = np.random.default_rng(33)
    n_v = 1 << 12
    raw = rng.zipf(1.4, (50_000, 2)) % n_v
    edges = [(int(a), int(b), 1.0) for a, b in raw if a != b]
    s = edge_stream_from_edges(edges, vertex_capacity=n_v,
                               chunk_size=1 << 13)
    h = host_spanner(s, k, max_degree=32)
    got = h.final_edges()
    check_spanner_properties([(a, b) for a, b, _ in edges], got, k)
    # Zipf hubs overflow a 32-slot row cap; the counter must have seen it
    # (the stretch property above held anyway — conservative degradation).
    assert h.deg_overflow > 0


@pytest.mark.skipif(not _toolchain(), reason="native toolchain unavailable")
def test_host_spanner_overflow_poisons_state():
    # An edge-list overflow mid-stream must fail fast on every later
    # access — re-draining a fresh stream iterator into half-folded state
    # would silently corrupt the spanner.
    from gelly_tpu.library.spanner import host_spanner

    edges = [(i, i + 1, 1.0) for i in range(40)]  # path: every edge kept
    s = edge_stream_from_edges(edges, vertex_capacity=64, chunk_size=8)
    h = host_spanner(s, 2, max_degree=8, max_edges=10)
    with pytest.raises(ValueError, match="overflow"):
        h.final_edges()
    with pytest.raises(RuntimeError, match="previously failed"):
        h.final_edges()
    with pytest.raises(RuntimeError, match="previously failed"):
        h.deg_overflow


@pytest.mark.skipif(not _toolchain(), reason="native toolchain unavailable")
def test_spanner_ingest_codec_single_chunk_exact():
    # One chunk spanning the whole stream: the chunk-local spanner equals
    # the stream-order spanner, and re-gating it into an empty global
    # reproduces the same decisions — codec result == plain result.
    rng = np.random.default_rng(6)
    n_v = 64
    edges = [(int(a), int(b), 1.0)
             for a, b in rng.integers(0, n_v, (400, 2))]

    def run(**kw):
        s = edge_stream_from_edges(edges, vertex_capacity=n_v,
                                   chunk_size=512)
        summ = s.aggregate(
            spanner(n_v, 3, **kw), mesh=mesh_lib.make_mesh(1),
            merge_every=4,
        ).result()
        return spanner_edges(summ, s.ctx)

    assert run(ingest_combine=True, payload_cap=256) == run()


@pytest.mark.skipif(not _toolchain(), reason="native toolchain unavailable")
@pytest.mark.parametrize("sparse", [False, True])
def test_spanner_ingest_codec_multichunk_stretch(sparse):
    # Multi-chunk codec: each re-gate level relaxes the bound by a factor
    # of k; this single-shard single-merge-window run has two levels
    # (chunk-local gate + device re-gate) — assert subset + k^2 stretch.
    rng = np.random.default_rng(15)
    n_v = 96
    edges = [(int(a), int(b), 1.0)
             for a, b in rng.integers(0, n_v, (600, 2)) if a != b]
    k = 2
    kw = dict(ingest_combine=True, max_edges=1024, payload_cap=256)
    if sparse:
        kw["max_degree"] = 32
    s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=64)
    summ = s.aggregate(
        spanner(n_v, k, **kw), mesh=mesh_lib.make_mesh(1), merge_every=4,
        fold_batch=4,
    ).result()
    got = spanner_edges(summ, s.ctx)
    eset = {frozenset(e) for e in ((a, b) for a, b, _ in edges)}
    for e in got:
        assert frozenset(e) in eset
    adj: dict = {}
    for a, b in got:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    for a, b, _ in edges:
        assert bfs_dist(adj, a, b) <= k * k, (a, b)


@pytest.mark.slow  # tier-1 budget: the dedups/scan-gate twin stays in tier
def test_batched_gate_k2_properties_and_pruning():
    """The gate_batch fold (closed-form distance-2 gate, VERDICT r4
    item 9) must uphold every spanner property — subset, stretch <= 2,
    connectivity — and still prune within-2 edges that arrive AFTER
    their witnesses (cross-sub-batch pruning is exact; only intra-step
    redundancy is conservative)."""
    from gelly_tpu.library.spanner import spanner, spanner_edges

    rng = np.random.default_rng(21)
    n_v = 64
    edges = list({(int(a), int(b))
                  for a, b in rng.integers(0, n_v, (300, 2)) if a != b})
    s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=32)
    agg = spanner(n_v, 2, max_degree=32, max_edges=1024, gate_batch=8)
    summary = s.aggregate(agg, merge_every=4).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, 2)
    # A hub star then its leaves' clique, folded within ONE window on ONE
    # shard (cross-shard acceptance is conservative by design — split
    # locals each see a fraction of the adjacency): the star lands first,
    # so every leaf-leaf edge is within 2 when gated — all pruned.
    star = [(0, i) for i in range(1, 9)]
    clique = [(a, b) for a in range(1, 9) for b in range(a + 1, 9)]
    s2 = edge_stream_from_edges(star + clique, vertex_capacity=16,
                                chunk_size=8)
    agg2 = spanner(16, 2, max_degree=16, max_edges=64, gate_batch=8)
    got2 = spanner_edges(
        s2.aggregate(agg2, mesh=mesh_lib.make_mesh(1),
                     merge_every=16).result(),
        s2.ctx,
    )
    assert {frozenset(e) for e in got2} == {frozenset(e) for e in star}


def test_batched_gate_k2_dedups_and_matches_scan_gate_properties():
    from gelly_tpu.library.spanner import spanner, spanner_edges

    # Duplicate-heavy stream: duplicates inside one sub-batch dedup;
    # across sub-batches the gate rejects them (direct neighbors).
    edges = [(1, 2)] * 20 + [(2, 3)] * 20 + [(1, 3)] * 20
    s = edge_stream_from_edges(edges, vertex_capacity=8, chunk_size=16)
    agg = spanner(8, 2, max_degree=8, max_edges=32, gate_batch=4)
    got = spanner_edges(s.aggregate(agg, merge_every=1).result(), s.ctx)
    assert len(got) <= 3
    check_spanner_properties(edges, got, 2)


def test_batched_gate_requires_k2():
    from gelly_tpu.library.spanner import spanner

    with pytest.raises(ValueError, match="k == 2"):
        spanner(16, 3, max_degree=8, gate_batch=8)
