"""Spanner property tests (set-level, as the reference's unit test does
scenario-wise — T/util/AdjacencyListGraphTest.java:57-87; exact edge parity
is order-dependent by design)."""

import itertools

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.library.spanner import spanner, spanner_edges
from gelly_tpu.parallel import mesh as mesh_lib


def bfs_dist(adj: dict, a: int, b: int) -> float:
    if a == b:
        return 0
    frontier, seen, d = {a}, {a}, 0
    while frontier:
        d += 1
        frontier = {n for f in frontier for n in adj.get(f, ())} - seen
        if b in frontier:
            return d
        seen |= frontier
    return float("inf")


def check_spanner_properties(edges, got, k):
    eset = {frozenset(e) for e in edges}
    # 1. spanner edges are input edges
    for e in got:
        assert frozenset(e) in eset, e
    # 2. every input edge's endpoints within k hops in the spanner
    adj: dict = {}
    for a, b in got:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    for a, b in edges:
        assert bfs_dist(adj, a, b) <= k, (a, b)


@pytest.mark.parametrize("k", [2, 3])
def test_spanner_properties_random_graph(k):
    rng = np.random.default_rng(9)
    verts = list(range(24))
    edges = list({(int(a), int(b))
                  for a, b in rng.integers(0, 24, (80, 2)) if a != b})
    s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=8)
    agg = spanner(32, k)
    summary = s.aggregate(agg, merge_every=2).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, k)
    assert len(got) <= len(edges)


def test_spanner_keeps_tree_edges():
    # A tree has no redundant edges: the spanner must keep all of them.
    edges = [(i, i + 1) for i in range(10)] + [(3, 20), (20, 21)]
    s = edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=4)
    summary = s.aggregate(spanner(32, 3), merge_every=1).result()
    got = spanner_edges(summary, s.ctx)
    assert {frozenset(e) for e in got} == {frozenset(e) for e in edges}


def test_spanner_prunes_dense_clique():
    # K8 with k=2: once a hub path exists, most edges are within 2 hops.
    edges = list(itertools.combinations(range(8), 2))
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=4)
    summary = s.aggregate(spanner(16, 2), merge_every=1).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, 2)
    assert len(got) < len(edges)  # must prune something in a clique


def test_spanner_multi_shard_merge(devices):
    m = mesh_lib.make_mesh(8)
    rng = np.random.default_rng(4)
    edges = list({(int(a), int(b))
                  for a, b in rng.integers(0, 16, (60, 2)) if a != b})
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=8)
    summary = s.aggregate(spanner(16, 2), mesh=m, merge_every=2).result()
    got = spanner_edges(summary, s.ctx)
    check_spanner_properties(edges, got, 2)


def test_spanner_overflow_flag():
    edges = [(i, i + 1) for i in range(10)]
    s = edge_stream_from_edges(edges, vertex_capacity=16, chunk_size=4)
    summary = s.aggregate(spanner(16, 2, max_edges=4), merge_every=1).result()
    with pytest.raises(RuntimeError, match="overflow"):
        spanner_edges(summary, s.ctx)
