"""Union-find kernel unit tests — DisjointSetTest analog
(T/util/DisjointSetTest.java:32-78)."""

import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu.ops.unionfind import (
    component_labels,
    fresh_forest,
    merge_forest_stack,
    merge_forests,
    pointer_jump,
    union_edges,
)


def labels_of(parent, n_used):
    return np.asarray(pointer_jump(parent))[:n_used].tolist()


def test_union_basic_chain():
    p = fresh_forest(8)
    src = jnp.array([0, 1, 2], jnp.int32)
    dst = jnp.array([1, 2, 3], jnp.int32)
    p = union_edges(p, src, dst, jnp.ones(3, bool))
    assert labels_of(p, 4) == [0, 0, 0, 0]


def test_union_respects_valid_mask():
    p = fresh_forest(8)
    src = jnp.array([0, 2], jnp.int32)
    dst = jnp.array([1, 3], jnp.int32)
    p = union_edges(p, src, dst, jnp.array([True, False]))
    assert labels_of(p, 4) == [0, 0, 2, 3]


def test_union_order_free_canonical():
    # Same component set regardless of edge order; root is the min slot.
    edges = [(4, 2), (2, 7), (7, 1), (5, 6)]
    for perm in ([0, 1, 2, 3], [3, 2, 1, 0], [1, 3, 0, 2]):
        p = fresh_forest(8)
        src = jnp.array([edges[i][0] for i in perm], jnp.int32)
        dst = jnp.array([edges[i][1] for i in perm], jnp.int32)
        p = union_edges(p, src, dst, jnp.ones(4, bool))
        lab = labels_of(p, 8)
        assert lab[1] == lab[2] == lab[4] == lab[7] == 1
        assert lab[5] == lab[6] == 5


def test_merge_even_odd_forests():
    # DisjointSetTest's merge scenario: an "evens" forest and an "odds"
    # forest over 18 elements merge into 2 roots (:60-78).
    n = 18
    evens = fresh_forest(32)
    odds = fresh_forest(32)
    e = jnp.array(range(0, n - 2, 2), jnp.int32)
    evens = union_edges(evens, e, e + 2, jnp.ones_like(e, dtype=bool))
    o = jnp.array(range(1, n - 2, 2), jnp.int32)
    odds = union_edges(odds, o, o + 2, jnp.ones_like(o, dtype=bool))
    merged = merge_forests(evens, odds)
    lab = labels_of(merged, n)
    assert set(lab[0::2]) == {0}
    assert set(lab[1::2]) == {1}
    assert len(set(lab)) == 2


def test_merge_stack_equals_pairwise():
    n = 16
    f1 = union_edges(fresh_forest(n), jnp.array([0]), jnp.array([1]),
                     jnp.ones(1, bool))
    f2 = union_edges(fresh_forest(n), jnp.array([1]), jnp.array([2]),
                     jnp.ones(1, bool))
    f3 = union_edges(fresh_forest(n), jnp.array([5]), jnp.array([6]),
                     jnp.ones(1, bool))
    stacked = jnp.stack([f1, f2, f3])
    m = merge_forest_stack(stacked)
    pairwise = merge_forests(merge_forests(f1, f2), f3)
    np.testing.assert_array_equal(np.asarray(m), np.asarray(pairwise))
    lab = labels_of(m, 8)
    assert lab[0] == lab[1] == lab[2] == 0
    assert lab[5] == lab[6] == 5


def test_component_labels_unseen_is_minus_one():
    p = fresh_forest(8)
    seen = jnp.zeros(8, bool).at[jnp.array([0, 1])].set(True)
    p = union_edges(p, jnp.array([0]), jnp.array([1]), jnp.ones(1, bool))
    lab = np.asarray(component_labels(p, seen))
    assert lab.tolist() == [0, 0, -1, -1, -1, -1, -1, -1]


def test_union_pairs_compact_matches_union_edges():
    import jax.numpy as jnp

    from gelly_tpu.ops.unionfind import (
        fresh_forest,
        union_edges,
        union_pairs_compact,
    )

    rng = np.random.default_rng(43)
    n = 512
    for trial in range(5):
        src = jnp.asarray(rng.integers(0, n, 200), jnp.int32)
        dst = jnp.asarray(rng.integers(0, n, 200), jnp.int32)
        ok = jnp.asarray(rng.random(200) < 0.8)
        a = union_edges(fresh_forest(n), src, dst, ok)
        b = union_pairs_compact(fresh_forest(n), src, dst, ok)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Chained folds (flat-input invariant maintained across calls).
        src2 = jnp.asarray(rng.integers(0, n, 150), jnp.int32)
        dst2 = jnp.asarray(rng.integers(0, n, 150), jnp.int32)
        ok2 = jnp.asarray(rng.random(150) < 0.8)
        a2 = union_edges(a, src2, dst2, ok2)
        b2 = union_pairs_compact(b, src2, dst2, ok2)
        np.testing.assert_array_equal(np.asarray(a2), np.asarray(b2))
        # Result is flat (the invariant consumers rely on).
        np.testing.assert_array_equal(np.asarray(b2), np.asarray(b2)[np.asarray(b2)])


def test_union_pairs_parity_compact_matches_union_edges_parity():
    import jax.numpy as jnp

    from gelly_tpu.ops.parity_unionfind import (
        fresh_parity_forest,
        union_edges_parity,
        union_pairs_parity_compact,
    )

    rng = np.random.default_rng(47)
    n = 512
    for trial in range(5):
        f_a = f_b = fresh_parity_forest(n)
        # Chained folds; later rounds likely create odd cycles, so both
        # the structure AND the sticky failed bit must track.
        for round_ in range(3):
            m = 150
            u = jnp.asarray(rng.integers(0, n, m), jnp.int32)
            v = jnp.asarray(rng.integers(0, n, m), jnp.int32)
            q = jnp.asarray(rng.integers(0, 2, m), jnp.int32)
            ok = jnp.asarray(rng.random(m) < 0.8)
            f_a = union_edges_parity(f_a, u, v, q, ok)
            f_b = union_pairs_parity_compact(f_b, u, v, q, ok)
            np.testing.assert_array_equal(
                np.asarray(f_a.parent), np.asarray(f_b.parent),
            )
            assert bool(f_a.failed) == bool(f_b.failed), (trial, round_)
            if not bool(f_a.failed):
                # The 2-coloring is unique per component only while the
                # constraints are consistent; after an odd cycle the
                # coloring is undefined (the reference collapses to
                # (false, {})) and the implementations may settle
                # different rel values.
                np.testing.assert_array_equal(
                    np.asarray(f_a.rel), np.asarray(f_b.rel),
                )
        # Flat-forest invariant holds for the compact result.
        p = np.asarray(f_b.parent)
        np.testing.assert_array_equal(p, p[p])
        r = np.asarray(f_b.rel)
        assert (r[p == np.arange(n)] == 0).all()


# ---------------- pair-sized kernels (compact-space folds) -------------- #


def _pair_oracle(m, all_pairs):
    parent = list(range(m))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in all_pairs:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    return [find(x) for x in range(m)]


def test_union_pairs_rooted_matches_union_edges():
    from gelly_tpu.ops.unionfind import union_pairs_rooted

    rng = np.random.default_rng(3)
    m = 64
    p = fresh_forest(m)
    all_pairs = []
    for _ in range(5):  # sequential calls over one never-flattened forest
        src = rng.integers(0, m, 20).astype(np.int32)
        dst = rng.integers(0, m, 20).astype(np.int32)
        ok = rng.random(20) < 0.8
        all_pairs += [(int(a), int(b))
                      for a, b, o in zip(src, dst, ok) if o]
        p = union_pairs_rooted(p, jnp.asarray(src), jnp.asarray(dst),
                               jnp.asarray(ok))
    assert labels_of(p, m) == _pair_oracle(m, all_pairs)


def test_union_pairs_star_deep_chain_no_severed_edges():
    # Deterministic regression for the severed-edge bug (code-review r4):
    # build croot chain 20->19->18->17->16 over five calls, then union
    # (20, 3). The depth-2 fast chase stops at INTERIOR node 18; an
    # unmasked hook would overwrite p[18]=17 with 3, disconnecting
    # {17, 16} — and the depth-3 convergence check then reads (20, 3) as
    # satisfied, so the exact fallback never repairs the split. The root
    # mask must reject that hook and route the pair to the exact loop.
    from gelly_tpu.ops.unionfind import union_pairs_star

    p = fresh_forest(24)
    rows = [(20, 19), (19, 18), (18, 17), (17, 16), (20, 3)]
    for a, root in rows:
        v = jnp.array([root, a], jnp.int32)
        ri = jnp.array([0, 0], jnp.int32)
        p = union_pairs_star(p, v, ri, jnp.ones(2, bool))
    lab = labels_of(p, 24)
    assert len({lab[x] for x in (3, 16, 17, 18, 19, 20)}) == 1, lab


@pytest.mark.slow  # tier-1 budget: deep-chain twin stays in tier
def test_union_pairs_star_sequential_calls_fuzz():
    # Regression for the severed-edge bug (code-review r4): unrolled fast
    # rounds hooking at a depth-limited NON-root overwrote its real parent
    # edge, disconnecting ancestors and silently splitting components
    # built by earlier dispatches. Adversarial star payloads over many
    # sequential calls on one never-flattened forest, vs a pair oracle.
    from gelly_tpu.ops.unionfind import union_pairs_star

    for seed in range(8):
        rng = np.random.default_rng(seed)
        m = 24
        p = fresh_forest(m)
        all_pairs = []
        for _ in range(6):
            # One star-forest row: unique v, row-local root indices ri.
            n_row = int(rng.integers(2, m))
            v = rng.permutation(m)[:n_row].astype(np.int32)
            # Random forest over the row: each entry points at a random
            # earlier entry (or itself) -> ri is a valid root index map.
            parent_idx = np.arange(n_row)
            for j in range(1, n_row):
                if rng.random() < 0.7:
                    parent_idx[j] = int(rng.integers(0, j))
            # Path-compress to row roots.
            for j in range(n_row):
                r = j
                while parent_idx[r] != r:
                    r = parent_idx[r]
                parent_idx[j] = r
            ri = parent_idx.astype(np.int32)
            all_pairs += [(int(v[j]), int(v[ri[j]]))
                          for j in range(n_row)]
            p = union_pairs_star(
                p, jnp.asarray(v), jnp.asarray(ri),
                jnp.ones(n_row, bool),
            )
        got = labels_of(p, m)
        want = _pair_oracle(m, all_pairs)
        assert got == want, (seed, got, want)


# ------------------- sort-dedup raw fold (round 5) -------------------- #


def test_union_edges_dedup_matches_union_edges():
    from gelly_tpu.ops.unionfind import union_edges_dedup

    rng = np.random.default_rng(12)
    n = 256
    for seed in range(4):
        rng = np.random.default_rng(seed)
        src = rng.integers(0, n, 500).astype(np.int32)
        dst = rng.integers(0, n, 500).astype(np.int32)
        valid = rng.random(500) < 0.85
        p1 = union_edges(
            fresh_forest(n), jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(valid),
        )
        p2 = union_edges_dedup(
            fresh_forest(n), jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(valid), unique_cap=256, tail_cap=64,
        )
        assert labels_of(p1, n) == labels_of(p2, n), seed


def test_union_edges_dedup_cap_overflow_exact():
    # ALL pairs distinct and unique_cap tiny: the full-width exact
    # fallback must fire and still produce correct labels.
    from gelly_tpu.ops.unionfind import union_edges_dedup

    n = 128
    src = np.arange(0, 126, 2, dtype=np.int32)
    dst = (np.arange(0, 126, 2, dtype=np.int32) + 1)
    p = union_edges_dedup(
        fresh_forest(n), jnp.asarray(src), jnp.asarray(dst),
        jnp.ones(src.shape[0], bool), unique_cap=8, tail_cap=4,
    )
    lab = labels_of(p, 126)
    assert lab == [2 * (i // 2) for i in range(126)]


def test_union_edges_dedup_tail_overflow_exact():
    # Long chain: the depth-3 rounds leave most pairs unresolved, the
    # tail cap overflows, and the exact distinct-pair fallback finishes.
    from gelly_tpu.ops.unionfind import union_edges_dedup

    n = 128
    src = np.arange(0, 100, dtype=np.int32)
    dst = np.arange(1, 101, dtype=np.int32)
    p = union_edges_dedup(
        fresh_forest(n), jnp.asarray(src), jnp.asarray(dst),
        jnp.ones(100, bool), unique_cap=128, tail_cap=4,
    )
    assert labels_of(p, 101) == [0] * 101


def test_union_edges_dedup_sequential_folds():
    # Streaming shape: repeated folds into the same forest, components
    # lowered across folds, parity vs the generic kernel every step.
    from gelly_tpu.ops.unionfind import union_edges_dedup

    n = 512
    rng = np.random.default_rng(33)
    p1 = fresh_forest(n)
    p2 = fresh_forest(n)
    for step in range(5):
        src = (rng.zipf(1.5, 300) % n).astype(np.int32)
        dst = (rng.zipf(1.5, 300) % n).astype(np.int32)
        ok = jnp.ones(300, bool)
        p1 = union_edges(p1, jnp.asarray(src), jnp.asarray(dst), ok)
        p2 = union_edges_dedup(
            p2, jnp.asarray(src), jnp.asarray(dst), ok,
            unique_cap=256, tail_cap=64,
        )
        assert labels_of(p1, n) == labels_of(p2, n), step
