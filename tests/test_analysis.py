"""gelly_tpu.analysis: ABI cross-checker, jit-hazard linter, sanitizer
lane — plus regression tests for the native-session hardening that rode
along (negative-id rejection, rebuild overflow, finalize teardown)."""

import os
import shutil
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from gelly_tpu.analysis import abi, jitlint, sanitize

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
NATIVE_DIR = os.path.join(REPO, "native")
BINDINGS = os.path.join(REPO, "gelly_tpu", "utils", "native.py")


def _toolchain():
    return shutil.which("g++") is not None


# --------------------------------------------------------------------- #
# ABI cross-checker

def test_abi_clean_on_repo_tip():
    findings = abi.cross_check(NATIVE_DIR, BINDINGS)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_abi_parses_every_native_symbol():
    # The checker must actually see the full surface: every symbol the
    # bindings module declares exists in some extern "C" block and
    # vice versa (the clean diff above is vacuous if either parse came
    # back empty).
    import glob

    decls = {}
    for cc in glob.glob(os.path.join(NATIVE_DIR, "*.cc")):
        ds, fs = abi.parse_extern_c(cc)
        assert fs == []
        decls.update((d.name, d) for d in ds)
    bindings, fs = abi.parse_ctypes_bindings(BINDINGS)
    assert fs == []
    assert set(decls) == set(bindings)
    assert len(bindings) >= 25  # the full native surface, not a subset
    # every binding is complete — restype AND argtypes
    for b in bindings.values():
        assert b.restype is not None, b.name
        assert b.argtypes is not None, b.name


FIXTURE_CC = textwrap.dedent("""\
    // fixture: deliberate ABI drift against fixture_bindings.py
    #include <cstdint>

    extern "C" {

    // ok: bound correctly
    int good_fn(const int32_t* a, int64_t n);

    // AB004: bound as _i32p but declared int64_t*
    int width_fn(const int64_t* a, int64_t n) { return n > 0 ? 1 : 0; }

    // AB003: bound with 2 params, declared with 3
    int arity_fn(const int32_t* a, int64_t n, int32_t flags);

    // AB005: returns int64_t, bound as c_int
    int64_t ret_fn(void);

    // AB001: never bound
    void unbound_fn(int32_t x);

    }  // extern "C"
""")

FIXTURE_PY = textwrap.dedent("""\
    import ctypes

    _i32p = ctypes.POINTER(ctypes.c_int32)


    def bind(lib):
        lib.good_fn.restype = ctypes.c_int
        lib.good_fn.argtypes = [_i32p, ctypes.c_int64]
        lib.width_fn.restype = ctypes.c_int
        lib.width_fn.argtypes = [_i32p, ctypes.c_int64]
        lib.arity_fn.restype = ctypes.c_int
        lib.arity_fn.argtypes = [_i32p, ctypes.c_int64]
        lib.ret_fn.restype = ctypes.c_int
        lib.ret_fn.argtypes = []
        lib.ghost_fn.restype = ctypes.c_int     # AB002: no such symbol
        lib.ghost_fn.argtypes = []
""")


@pytest.fixture
def abi_fixture(tmp_path):
    native = tmp_path / "native"
    native.mkdir()
    (native / "fixture.cc").write_text(FIXTURE_CC)
    py = tmp_path / "fixture_bindings.py"
    py.write_text(FIXTURE_PY)
    return str(native), str(py)


def test_abi_detects_seeded_mismatches(abi_fixture):
    native, py = abi_fixture
    findings = abi.cross_check(native, py)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"AB001", "AB002", "AB003", "AB004", "AB005"}
    [w] = by_rule["AB004"]
    assert "width_fn" in w.message and "'i32*'" in w.message \
        and "'i64*'" in w.message
    [a] = by_rule["AB003"]
    assert "arity_fn" in a.message and "2" in a.message and "3" in a.message
    [r] = by_rule["AB005"]
    assert "ret_fn" in r.message
    [u] = by_rule["AB001"]
    assert "unbound_fn" in u.message
    [g] = by_rule["AB002"]
    assert "ghost_fn" in g.message
    # good_fn must NOT be reported
    assert not any("good_fn" in f.message for f in findings)


def test_abi_c_parser_handles_comments_strings_and_bodies(tmp_path):
    cc = tmp_path / "c.cc"
    cc.write_text(textwrap.dedent("""\
        extern "C" {
        // commented_out(int x);
        /* also_commented(int x); */
        int real_fn(const char* s, double d) {
          const char* brace = "{ not a block }";  // string literal brace
          if (d > 0) { return s[0]; }
          return 0;
        }
        unsigned char byte_fn(unsigned char b);
        }
    """))
    decls, findings = abi.parse_extern_c(str(cc))
    assert findings == []
    names = {d.name: d for d in decls}
    assert set(names) == {"real_fn", "byte_fn"}
    assert names["real_fn"].params == ["char*", "f64"]
    assert names["real_fn"].ret == "i32"
    assert names["byte_fn"].ret == "u8"
    assert names["byte_fn"].params == ["u8"]


@pytest.mark.slow  # tier-1 budget: CI heavy lane; abi tip-clean stays in tier
def test_abi_cli_exit_codes(abi_fixture, tmp_path):
    native, py = abi_fixture
    clean = subprocess.run(
        [sys.executable, "-m", "gelly_tpu.analysis", "--skip-jitlint"],
        capture_output=True, text=True, cwd=REPO)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    dirty = subprocess.run(
        [sys.executable, "-m", "gelly_tpu.analysis", "--skip-jitlint",
         "--native-dir", native, "--bindings", py],
        capture_output=True, text=True, cwd=REPO)
    assert dirty.returncode == 1
    assert "AB004" in dirty.stdout


# --------------------------------------------------------------------- #
# jit-hazard linter

JIT_FIXTURE = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial


    @jax.jit
    def np_on_traced(x):
        return np.cumsum(x)                      # GL001


    @jax.jit
    def np_static_ok(x):
        return x.reshape((int(np.prod(x.shape)),))  # shapes are static


    @jax.jit
    def branch_on_traced(x):
        if x.sum() > 0:                          # GL002
            return x
        return -x


    @jax.jit
    def while_on_traced(x):
        while x > 0:                             # GL002
            x = x - 1
        return x


    @partial(jax.jit, static_argnames=("n",))
    def branch_on_static(x, n):
        if n > 2:                                # static arg: clean
            return x * n
        return x


    @jax.jit
    def structural_ok(x, valid=None):
        if valid is None:                        # structural: clean
            return x
        if x.ndim == 2:                          # shape read: clean
            return x[0]
        return jnp.where(valid, x, 0)


    @jax.jit
    def coerce_traced(x):
        return float(x) + x.item()               # GL003 (twice)


    @jax.jit
    def stack_dict(d):
        return jnp.stack(list(d.values()))       # GL004


    @jax.jit
    def untyped_literal(x):
        return x + jnp.full((4,), 0.25)          # GL005


    @jax.jit
    def typed_literal_ok(x):
        return x + jnp.full((4,), 0.25, jnp.float32)


    @jax.jit
    def suppressed(x):
        return np.cumsum(x)  # graphlint: disable=GL001


    def helper(v, flag):
        if flag:                                 # untraced at call: clean
            v = v * 2
        return np.asarray(v)                     # GL001 via expansion


    @jax.jit
    def calls_helper(x):
        return helper(x, True)


    def jit_by_call(x):
        if x > 0:                                # GL002 (jax.jit(f) form)
            return x
        return -x


    run = jax.jit(jit_by_call)
""")


@pytest.fixture
def lint_fixture(tmp_path):
    p = tmp_path / "jit_fixture.py"
    p.write_text(JIT_FIXTURE)
    return str(tmp_path), str(p)


def test_jitlint_clean_on_repo_tip():
    findings = jitlint.lint_paths(REPO, [os.path.join(REPO, "gelly_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_jitlint_detects_each_seeded_rule(lint_fixture):
    root, path = lint_fixture
    findings = jitlint.lint_paths(root, [path])
    lines = {}
    fixture_lines = JIT_FIXTURE.splitlines()
    for f in findings:
        lines.setdefault(f.rule, set()).add(fixture_lines[f.line - 1].strip())
    assert set(lines) == {"GL001", "GL002", "GL003", "GL004", "GL005"}
    assert any("np.cumsum" in ln for ln in lines["GL001"])
    assert any("helper" not in ln and "np.asarray(v)" in ln
               for ln in lines["GL001"])  # one-level call expansion
    gl2 = " ".join(lines["GL002"])
    assert "x.sum()" in gl2 and "while x > 0" in gl2
    assert any("jax.jit(f) form" in ln or "x > 0" in ln
               for ln in lines["GL002"])  # jax.jit(fn) call form
    assert any("float(x)" in ln for ln in lines["GL003"])
    assert any("d.values" in ln for ln in lines["GL004"])
    assert any("0.25" in ln for ln in lines["GL005"])
    # exemptions: statics, structural tests, shape reads, dtype'd literal
    clean_fns = ("np_static_ok", "branch_on_static", "structural_ok",
                 "typed_literal_ok", "suppressed")
    for f in findings:
        for fn in clean_fns:
            assert fn not in f.message, f.render()


def test_jitlint_suppression_is_line_scoped(tmp_path):
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""\
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = np.log(x)  # graphlint: disable=GL001
            b = np.exp(x)
            return a + b
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert len(findings) == 1
    assert findings[0].rule == "GL001"
    assert "np.exp" in findings[0].message


def test_jitlint_descends_into_pallas_kernels(tmp_path):
    # pl.pallas_call(kernel, ...) sites descend into the kernel with ref
    # params traced — hazards inside kernels surface, including through
    # a functools.partial(kernel, ...) wrapper, and including kernels
    # only reachable from non-jitted builder functions.
    p = tmp_path / "kern.py"
    p.write_text(textwrap.dedent("""\
        import numpy as np
        import jax
        import jax.numpy as jnp
        from functools import partial
        from jax.experimental import pallas as pl

        def _bad_kernel(a_ref, o_ref):
            x = a_ref[:]
            if pl.program_id(0) == 0:
                o_ref[:] = x
            o_ref[:] = jnp.asarray(np.sum(x))

        def _partial_kernel(n, a_ref, o_ref):
            o_ref[:] = a_ref[:] + np.int32(n)

        def build(x):
            g = pl.pallas_call(
                _bad_kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))
            h = pl.pallas_call(
                partial(_partial_kernel, 3),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))
            return g(x), h(x)
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert any("np.sum" in m for m in by_rule.get("GL001", []))
    assert any("_partial_kernel" in m and "np.int32" in m
               for m in by_rule.get("GL001", []))
    assert any("pl.program_id" in m for m in by_rule.get("GL002", []))
    for f in findings:
        assert "pallas kernel" in f.message, f.render()


def test_jitlint_no_false_positives_on_pallas_plumbing(tmp_path):
    # Grid/meta plumbing (pl.ds, pl.cdiv, pl.BlockSpec, pltpu.* scratch
    # constructors, pl.when decorators, partial-bound ints) must not
    # produce GLxxx findings — the regression the repo's own kernels
    # gate on (see also test_jitlint_clean_on_repo_tip, which now
    # descends into gelly_tpu's real kernels).
    p = tmp_path / "clean.py"
    p.write_text(textwrap.dedent("""\
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _clean_kernel(s_ref, a_ref, o_ref):
            g = pl.program_id(0)
            base = s_ref[g] * jnp.int32(8)
            row = jax.lax.div(a_ref[:], jnp.int32(128))
            for t0 in range(0, 8, 4):
                o_ref[t0:t0 + 4] = row[t0:t0 + 4] + base

        def build(starts, x):
            spec = pl.BlockSpec((8, 128), lambda g, s: (g, 0))
            return pl.pallas_call(
                _clean_kernel,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1, grid=(4,),
                    in_specs=[spec], out_specs=spec),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(starts, x)
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_jitlint_plain_pallas_import_does_not_blind_jax_calls(tmp_path):
    # 'import jax.experimental.pallas' (no asname) binds the name 'jax';
    # treating THAT as a pallas alias would mark every jax.* call as
    # concrete plumbing and suppress real findings module-wide.
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""\
        import jax
        import jax.experimental.pallas
        import numpy as np

        @jax.jit
        def f(x):
            return np.log(x)
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert [f.rule for f in findings] == ["GL001"]


def test_jitlint_pallas_call_other_spellings_descend(tmp_path):
    # Fully-dotted jax.experimental.pallas.pallas_call and the bare
    # from-import both resolve their kernels.
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""\
        import numpy as np
        import jax
        import jax.experimental.pallas
        from jax.experimental.pallas import pallas_call

        def _k1(a_ref, o_ref):
            o_ref[:] = np.asarray(a_ref[:])

        def _k2(a_ref, o_ref):
            o_ref[:] = np.abs(a_ref[:])

        def build(x):
            shape = jax.ShapeDtypeStruct(x.shape, x.dtype)
            f = jax.experimental.pallas.pallas_call(_k1, out_shape=shape)
            g = pallas_call(_k2, out_shape=shape)
            return f(x), g(x)
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    kernels = {f.message.split("pallas kernel ")[1].split("]")[0]
               for f in findings}
    assert {"'_k1'", "'_k2'"} == kernels
    assert all(f.rule == "GL001" for f in findings)


def test_jitlint_cli_nonzero_on_fixture(lint_fixture):
    root, path = lint_fixture
    proc = subprocess.run(
        [sys.executable, "-m", "gelly_tpu.analysis", "--skip-abi",
         "--root", root, "--lint-path", path],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    for rule in ("GL001", "GL002", "GL003", "GL004", "GL005"):
        assert rule in proc.stdout, rule


def test_jitlint_host_clock_in_jit_flagged(tmp_path):
    # GL007: host clocks inside a jitted function execute once at trace
    # time — every import spelling is caught, and the suppression
    # comment is honored.
    p = tmp_path / "clock.py"
    p.write_text(textwrap.dedent("""\
        import time
        import time as _t
        import datetime as dt
        from time import perf_counter
        from datetime import datetime
        import jax

        @jax.jit
        def f(x):
            a = time.perf_counter()
            b = _t.monotonic()
            c = dt.datetime.now()
            d = datetime.utcnow()
            e = perf_counter()
            s = time.time()  # graphlint: disable=GL007
            return x + a + b + e
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert all(f.rule == "GL007" for f in findings)
    hits = {f.message.split("(")[0].split(":")[1].strip() for f in findings}
    assert hits == {"time.perf_counter", "_t.monotonic",
                    "dt.datetime.now", "datetime.utcnow", "perf_counter"}
    # the suppressed time.time() line produced no finding
    assert not any("time.time" in f.message for f in findings)


def test_jitlint_host_clock_shadowed_locals_not_flagged(tmp_path):
    # A parameter or local that SHADOWS a module-level time/perf_counter
    # import is an unrelated callable, not the stdlib clock — same
    # scoping discipline as GL006's donation bindings.
    p = tmp_path / "shadow.py"
    p.write_text(textwrap.dedent("""\
        import time
        from time import perf_counter
        import jax

        @jax.jit
        def param_shadows(x, perf_counter):
            return x + perf_counter(x)

        @jax.jit
        def local_shadows(x):
            time = make_table()
            return x + time.time()

        @jax.jit
        def still_flagged(x):
            return x + time.perf_counter()

        def make_table():
            return None
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].rule == "GL007"
    assert "still_flagged" in findings[0].message


def test_jitlint_host_clock_outside_jit_and_kernels(tmp_path):
    # Both ways: the same clock calls in a HOST function are legitimate
    # timing code and must not be flagged; inside a pallas kernel they
    # ARE flagged (kernels always compile).
    p = tmp_path / "clock2.py"
    p.write_text(textwrap.dedent("""\
        import time
        import jax
        from jax.experimental import pallas as pl

        def host_timing(fn):
            t0 = time.perf_counter()
            out = fn()
            return out, time.perf_counter() - t0

        def kernel(x_ref, o_ref):
            t = time.time()
            o_ref[...] = x_ref[...]

        def build(x):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].rule == "GL007"
    assert "pallas kernel" in findings[0].message
    assert "time.time" in findings[0].message


# --------------------------------------------------------------------- #
# sanitizer lane

def test_smoke_driver_runs_unsanitized():
    # The workload itself must hold before the sanitizers wrap it.
    pytest.importorskip("gelly_tpu.utils.native")
    if not _toolchain():
        pytest.skip("no native toolchain")
    assert sanitize.smoke() == []


@pytest.mark.sanitize
@pytest.mark.parametrize("mode", ["asan", "ubsan"])
def test_native_folds_clean_under_sanitizer(mode):
    if not _toolchain():
        pytest.skip("no native toolchain")
    if not sanitize.sanitizer_available(mode):
        pytest.skip(f"{mode} runtime unavailable")
    proc = sanitize.run_smoke(mode)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"mode={mode}" in proc.stdout


# --------------------------------------------------------------------- #
# native-session hardening regressions (satellites of this PR)

def _native_session():
    from gelly_tpu.utils import native

    if not native.compact_session_available():
        pytest.skip("native compact session unavailable")
    return native


def test_session_rejects_negative_ids():
    native = _native_session()
    s = native.NativeCompactSession(8)
    s.assign(np.array([3, 4], np.int32))
    with pytest.raises(ValueError, match="negative"):
        s.assign(np.array([5, -1], np.int32))
    # the failed call must not have assigned anything (atomic contract)
    assert s.assigned == 2
    out, bad = s.lookup(np.array([5], np.int32))
    assert bad == 1 and out.tolist() == [-1]


def test_compact_session_wrapper_rejects_negative_ids():
    from gelly_tpu.ops.compact_space import CompactIdSession

    s = CompactIdSession(8)
    with pytest.raises(ValueError, match="negative"):
        s.assign(np.array([1, -7], np.int32))
    assert s.assigned == 0


def test_session_rebuild_overflow_raises():
    native = _native_session()
    s = native.NativeCompactSession(4)
    with pytest.raises(ValueError, match="capacity"):
        s.rebuild(np.full(5, -1, np.int32))
    # at-capacity checkpoint still restores
    vo = np.array([9, 8, -1, 7], np.int32)
    s.rebuild(vo)
    assert s.assigned == 4
    assert s.lookup(np.array([7], np.int32))[0].tolist() == [3]


def test_compact_session_wrapper_rebuild_overflow_raises():
    from gelly_tpu.ops.compact_space import CompactIdSession

    s = CompactIdSession(4)
    with pytest.raises(ValueError, match="compact_capacity|capacity"):
        s.rebuild_from_vertex_of(np.full(6, -1, np.int32))


def test_session_overflow_still_rolls_back():
    native = _native_session()
    s = native.NativeCompactSession(3)
    s.assign(np.array([1, 2], np.int32))
    cids, new_ids, base = s.assign(np.array([5, 6], np.int32))
    assert (cids, new_ids, base) == (None, None, -1)
    assert s.assigned == 2
    # the rolled-back ids are re-assignable one at a time
    _, _, base = s.assign(np.array([5], np.int32))
    assert base == 2


def test_session_poison_blocks_reuse():
    # After a native allocation failure (-4) the C-side rollback itself
    # may have failed, leaving a probe table that aliases dropped cids —
    # the wrapper discards the handle and every later call must raise
    # instead of reading the corrupt table.
    native = _native_session()
    s = native.NativeCompactSession(8)
    s.assign(np.array([1], np.int32))
    s._poison()
    with pytest.raises(RuntimeError, match="discarded"):
        s.assign(np.array([2], np.int32))
    with pytest.raises(RuntimeError, match="discarded"):
        s.lookup(np.array([1], np.int32))
    assert not s._finalize.alive  # handle already destroyed, no leak


def test_jitlint_lints_shadowed_same_name_functions(tmp_path):
    # Two defs sharing a name (e.g. methods of different classes) must
    # not shadow each other out of the lint pass.
    p = tmp_path / "shadow.py"
    p.write_text(textwrap.dedent("""\
        import jax
        import numpy as np

        class A:
            @staticmethod
            @jax.jit
            def step(x):
                return np.cumsum(x)

        class B:
            @staticmethod
            def step(x):
                return x
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert [f.rule for f in findings] == ["GL001"]


def test_abi_findings_anchor_to_declaration_lines():
    decls, _ = abi.parse_extern_c(
        os.path.join(NATIVE_DIR, "chunk_combiner.cc"))
    with open(os.path.join(NATIVE_DIR, "chunk_combiner.cc")) as f:
        lines = f.read().splitlines()
    for d in decls:
        assert d.name in lines[d.line - 1], (d.name, d.line)


def test_finalize_teardown_is_idempotent_and_silent():
    native = _native_session()
    s = native.NativeCompactSession(4)
    fin = s._finalize
    del s
    assert not fin.alive  # GC ran the finalizer exactly once

    if not native.unit_segments_available():
        return
    b = native.UnitForestBuilder(8)
    b.add(np.array([0], np.int32), np.array([1], np.int32), None)
    b.finish()
    assert not b._finalize.alive
    with pytest.raises(RuntimeError, match="finished"):
        b.finish()
    del b  # second teardown is a no-op, not a double free


def test_finalize_survives_interpreter_shutdown():
    # __del__-based teardown could raise during interpreter shutdown
    # (module globals torn down before the object). weakref.finalize
    # runs via atexit instead; a subprocess holding live handles at exit
    # must terminate cleanly with an empty stderr.
    code = textwrap.dedent("""\
        import numpy as np
        from gelly_tpu.utils import native

        if native.compact_session_available():
            KEEP = native.NativeCompactSession(64)
            KEEP.assign(np.arange(10, dtype=np.int32))
        if native.unit_segments_available():
            B = native.UnitForestBuilder(16)
            B.add(np.array([0], np.int32), np.array([1], np.int32), None)
        print("alive")
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    assert "alive" in proc.stdout
    assert "Exception ignored" not in proc.stderr, proc.stderr


# ---------------------------------------------------------------------- #
# GL006 — donation use-after-free (caller-side rule)

DONATION_FIXTURE = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp
    from functools import partial

    fold = jax.jit(lambda s, c: s + c, donate_argnums=0)

    @partial(jax.jit, donate_argnums=(0,))
    def fold2(state, x):
        return state + x

    def bad_read_after(state, x):
        out = fold(state, x)
        return out + state                       # read after donation

    def bad_loop(state, chunks):
        for c in chunks:
            fold2(state, c)                      # loop without rebind
        return 0

    def bad_via_alias(state, x):
        g = fold
        out = g(state, x)
        y = state + 1                            # read via alias
        return out + y

    def good_rebind_in_loop(state, chunks):
        for c in chunks:
            state = fold(state, c)
        return state

    def good_rebind_later_in_loop(state, chunks):
        for c in chunks:
            tmp = fold(state, c)
            state = tmp                  # rebound before the back edge
        return state

    def good_loop_target_rebinds(states, x):
        outs = []
        for state in states:             # target binds a fresh element
            outs.append(fold(state, x))
        return outs

    def good_drop(state, x):
        return fold(state, x)

    def good_exclusive_branches(state, x, flag):
        if flag:
            out = fold(state, x)
        else:
            out = state + 1
        return out

    def good_suppressed(state, x):
        out = fold(state, x)
        return out + state  # graphlint: disable=GL006

    def good_deferred_closure(state, x):
        thunk = lambda: fold(state, x)   # noqa: E731 — never runs here
        y = state + 1                    # legitimate: nothing donated yet
        return thunk, y

    def good_closure_reads_donated(state, x):
        out = fold(state, x)
        thunk = lambda: state + 1        # noqa: E731 — deferred read: the
        state = out                      # closure runs only after the rebind
        return thunk, state

    def nested_factory(x):
        @partial(jax.jit, donate_argnums=(0,))
        def step(s, c):
            return s + c
        return step(jnp.zeros(()), x)

    def good_unrelated_same_name(x, y):
        def step(a, b):                  # plain local def: must NOT inherit
            return a + b                 # nested_factory's donated 'step'
        r = step(x, y)
        return r + x

    def good_local_shadows_module_donation(state, x):
        def fold(a, b):                  # shadows the module-level donated
            return a + b                 # 'fold' for this scope
        out = fold(state, x)
        return out + state

    def bad_nested_donated_local_def(state, x):
        @partial(jax.jit, donate_argnums=(0,))
        def step3(s, c):
            return s + c
        out = step3(state, x)
        return out + state               # read after local-def donation

    def good_param_shadows_donated(fold, s0, x):
        y = fold(s0, x)                  # param 'fold' is NOT the module
        return s0 + y                    # donated fold: unknown callable

    def good_plain_rebind_clears(s0, x):
        fold2 = lambda a, b: a           # noqa: E731 — plain rebind of a
        y = fold2(s0, x)                 # donated name: no donation here
        return s0 + y

    def good_for_target_shadows(fns, s0, x):
        for fold in fns:                 # loop target shadows the module
            s0 = s0 + fold(s0, x)        # donated 'fold'; reads are fine
        return s0
""")


def test_jitlint_donation_use_after_free(tmp_path):
    p = tmp_path / "donate.py"
    p.write_text(DONATION_FIXTURE)
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    gl6 = [f for f in findings if f.rule == "GL006"]
    assert len(gl6) == 4, "\n".join(f.render() for f in findings)
    src_lines = DONATION_FIXTURE.splitlines()
    flagged = {src_lines[f.line - 1].strip() for f in gl6}
    assert "return out + state                       # read after donation" \
        in flagged
    assert any("fold2(state, c)" in ln for ln in flagged)
    assert any("y = state + 1" in ln for ln in flagged)
    assert any("read after local-def donation" in ln for ln in flagged)
    # The safe idioms and the suppressed line produce nothing.
    for f in findings:
        assert "good_" not in f.message, f.render()


def test_jitlint_donation_engine_idiom_clean(tmp_path):
    # The engine's exact steady-state shape — donated fold rebound every
    # iteration, window close rebuilding state — must stay clean.
    p = tmp_path / "engine_like.py"
    p.write_text(textwrap.dedent("""\
        import jax

        fold = jax.jit(lambda s, c: s, donate_argnums=0)

        def drive(units, init):
            state = init()
            for u in units:
                state = fold(state, u)
                if u is None:
                    emit = state
                    state = init()
            return state
    """))
    findings = jitlint.lint_paths(str(tmp_path), [str(p)])
    assert [f for f in findings if f.rule == "GL006"] == [], \
        "\n".join(f.render() for f in findings)
