"""Pane-ring sliding windows + TTL decay (core/windows.py PaneRing +
engine/aggregation.py windowed mode).

Pins down the temporal engine's contracts: two-stack suffix aggregation
answers a W-pane sliding window in O(1) amortized combines per pane
close; windowed labels are bit-identical to a replay oracle (re-fold
only the last W panes' edges) on adversarial streams — hot vertex,
self-loops, TTL eviction then re-arrival of the same vertex id; one
checkpoint position covers ring + pane index + compact-id session
(generator abandon here, subprocess kill -9 below); snapshots serve a
consistent ``{window, labels}`` handle with the one-window staleness
bound; and every plane that cannot compose panes refuses loudly.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.core.windows import PaneRing
from gelly_tpu.engine.aggregation import (
    _compiled_tenant_plan,
    run_aggregation,
)
from gelly_tpu.engine.multiquery import fuse
from gelly_tpu.library.connected_components import (
    cc_labels_numpy,
    connected_components,
)
from gelly_tpu.library.degrees import degree_aggregate
from gelly_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.windows

N_V = 256
CH = 64


def _stream(src, dst, chunk_size=CH, n_v=N_V):
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, chunk_size=chunk_size,
                        table=IdentityVertexTable(n_v)), n_v)


def _zipf_stream(n_chunks=20, seed=7):
    """Hot-vertex (zipf) stream with self-loops sprinkled in."""
    rng = np.random.default_rng(seed)
    n_e = CH * n_chunks
    src = (rng.zipf(1.4, n_e) % N_V).astype(np.int64)
    dst = (rng.zipf(1.4, n_e) % N_V).astype(np.int64)
    src[::37] = dst[::37]  # self-loops: touched, but no forest edge
    return src, dst


def _replay(src, dst, upto_chunk, window_panes, merge_every):
    """The oracle: re-fold ONLY the last W panes' edges from scratch."""
    lo = max(0, upto_chunk * CH - window_panes * merge_every * CH)
    return cc_labels_numpy(src[lo:upto_chunk * CH],
                           dst[lo:upto_chunk * CH], None, N_V)


# ---------------------------------------------------------------------- #
# PaneRing: the two-stack queue on plain Python values


class TestPaneRing:
    def test_sliding_sum_parity_vs_naive(self):
        rng = np.random.default_rng(11)
        for w in (1, 2, 3, 7, 16):
            ring = PaneRing(w, lambda a, b: a + b)
            vals = []
            for i in range(5 * w + 3):
                v = int(rng.integers(0, 1000))
                vals.append(v)
                ring.push(v)
                assert ring.live == min(len(vals), w)
                assert ring.query() == sum(vals[-w:])

    def test_combines_amortized_constant(self):
        # Two-stack contract: total combines over N pushes is O(N),
        # independent of W — never a W-pane re-merge per close.
        for w in (4, 16, 64):
            ring = PaneRing(w, lambda a, b: a + b)
            n = 8 * w
            for i in range(n):
                ring.push(1)
                ring.query()
            # flip (~1/push amortized) + back_agg (~1/push) + query
            # front+back join (~1/query) stays under 4 per push+query.
            assert ring.combines <= 4 * n, (w, ring.combines, n)

    def test_non_commutative_order(self):
        # Window order matters: combine = concat must reproduce the
        # exact oldest->newest suffix, across flips and evictions.
        w = 5
        ring = PaneRing(w, lambda a, b: a + b)
        items = [[i] for i in range(23)]
        for i, it in enumerate(items):
            ring.push(it)
            lo = max(0, i + 1 - w)
            assert ring.query() == sum(items[lo:i + 1], [])
            assert ring.export_panes() == items[lo:i + 1]

    def test_export_reload_roundtrip(self):
        ring = PaneRing(4, lambda a, b: a + b)
        for i in range(11):
            ring.push(i)
        ring2 = PaneRing(4, lambda a, b: a + b)
        ring2.reload(ring.export_panes(), ring.panes_closed)
        assert ring2.query() == ring.query()
        assert ring2.panes_closed == ring.panes_closed
        ring.push(99), ring2.push(99)
        assert ring2.query() == ring.query()


# ---------------------------------------------------------------------- #
# windowed parity vs the replay oracle


def test_dense_cc_windowed_parity():
    src, dst = _zipf_stream()
    w, me = 4, 2
    agg = connected_components(N_V, merge="gather", codec="dense",
                               windowed=w)
    st = run_aggregation(agg, _stream(src, dst), merge_every=me)
    outs = [np.asarray(o) for o in st]
    assert len(outs) == 10 and st.stats["windows_closed"] == 10
    for i, got in enumerate(outs):
        want = _replay(src, dst, min((i + 1) * me, 20), w, me)
        assert np.array_equal(got, want), f"pane {i}"
    # O(1)-amortized combine bound, observable in the stream stats.
    assert st.stats["ring_combines"] <= 4 * st.stats["windows_closed"]


def test_degrees_windowed_parity():
    src, dst = _zipf_stream(seed=9)
    w, me = 4, 2
    dagg = degree_aggregate(N_V, codec="dense", windowed=w)
    # windowed rides the agg attribute: no engine kwarg needed.
    outs = [np.asarray(o) for o in
            run_aggregation(dagg, _stream(src, dst), merge_every=me)]
    for i, got in enumerate(outs):
        upto = min((i + 1) * me, 20)
        lo = max(0, upto * CH - w * me * CH)
        want = np.zeros(N_V, np.int64)
        np.add.at(want, src[lo:upto * CH], 1)
        np.add.at(want, dst[lo:upto * CH], 1)
        assert np.array_equal(got, want), f"pane {i}"


def test_compact_cc_windowed_ttl_parity():
    src, dst = _zipf_stream()
    w, me = 4, 2
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V,
                               windowed=w, ttl_panes=w)
    st = run_aggregation(agg, _stream(src, dst), merge_every=me,
                         mesh=mesh_lib.make_mesh(1),
                         prefetch_depth=0, h2d_depth=0, ingest_workers=1)
    outs = [np.asarray(o) for o in st]
    for i, got in enumerate(outs):
        want = _replay(src, dst, min((i + 1) * me, 20), w, me)
        assert np.array_equal(got, want), f"pane {i}"


def _two_phase_stream():
    """Phase A: vertices 0..99 active for 8 chunks; phase B: only
    100..119 for 16 chunks; vertex 5 re-arrives at the very end."""
    rng = np.random.default_rng(3)
    a, b = 8 * CH, 16 * CH
    src = np.empty(a + b, np.int64)
    dst = np.empty(a + b, np.int64)
    src[:a] = rng.integers(0, 100, a)
    dst[:a] = rng.integers(0, 100, a)
    src[a:] = rng.integers(100, 120, b)
    dst[a:] = rng.integers(100, 120, b)
    src[-3:] = 5
    dst[-3:] = 110
    return src, dst


def _two_phase_agg(w=3, ttl=4):
    return connected_components(N_V, codec="compact", compact_capacity=N_V,
                                windowed=w, ttl_panes=ttl)


def test_ttl_eviction_reclaims_capacity_and_rearrival():
    src, dst = _two_phase_stream()
    w, me, ttl = 3, 2, 4
    agg = _two_phase_agg(w, ttl)
    st = run_aggregation(agg, _stream(src, dst), merge_every=me,
                         mesh=mesh_lib.make_mesh(1),
                         prefetch_depth=0, h2d_depth=0, ingest_workers=1)
    outs, assigned = [], []
    for out in st:
        outs.append(np.asarray(out))
        assigned.append(agg.session.assigned)
    # Phase A populates ~100 slots; once its panes age past the TTL the
    # sweep releases them — steady state is bounded by the ACTIVE set.
    assert max(assigned[:5]) > 100
    assert assigned[-2] < 40, assigned
    # Parity at every close, including the evicted vertex 5 re-arriving
    # on a FRESH compact id at the end.
    for i, got in enumerate(outs):
        want = _replay(src, dst, min((i + 1) * me, 24), w, me)
        assert np.array_equal(got, want), f"pane {i}"


def test_checkpoint_resume_bit_parity(tmp_path):
    src, dst = _two_phase_stream()
    w, me = 3, 2
    ck = str(tmp_path / "win-ck.npz")
    kw = dict(merge_every=me, mesh=mesh_lib.make_mesh(1),
              prefetch_depth=0, h2d_depth=0,
              ingest_workers=1, checkpoint_path=ck, checkpoint_every=1)

    full = [np.asarray(o) for o in
            run_aggregation(_two_phase_agg(w), _stream(src, dst),
                            merge_every=me, mesh=mesh_lib.make_mesh(1),
                            prefetch_depth=0,
                            h2d_depth=0, ingest_workers=1)]

    it = iter(run_aggregation(_two_phase_agg(w), _stream(src, dst), **kw))
    for _ in range(5):
        next(it)
    it.close()  # abandon mid-stream; last durable checkpoint = pane 5

    st = run_aggregation(_two_phase_agg(w), _stream(src, dst),
                         resume=True, **kw)
    rest = [np.asarray(o) for o in st]
    # The checkpoint for pane k lands after pane k's yield, so resume
    # re-emits from the last checkpointed pane: align by tail.
    assert 0 < len(rest) < len(full)
    for i, (got, want) in enumerate(zip(rest, full[-len(rest):])):
        assert np.array_equal(got, want), f"tail pane {i}"


def test_snapshot_one_window_staleness():
    src, dst = _zipf_stream(seed=5)
    w, me = 4, 2
    agg = connected_components(N_V, merge="gather", codec="dense",
                               windowed=w)
    st = run_aggregation(agg, _stream(src, dst), merge_every=me)
    assert st.snapshot() is None  # nothing closed yet
    outs = []
    for out in st:
        outs.append(np.asarray(out))
        snap = st.snapshot()
        # Readable while the stream advances: the handle tracks the
        # newest CLOSED window — never ahead of a close, at most one
        # window behind the next one the producer is filling.
        assert snap is not None
        assert snap["window"] == len(outs)
        assert np.array_equal(np.asarray(snap["labels"]), outs[-1])
    snap = st.snapshot()
    assert snap["window"] == len(outs) == st.stats["windows_closed"]
    assert np.array_equal(np.asarray(snap["labels"]), outs[-1])


# ---------------------------------------------------------------------- #
# eligibility: planes that cannot compose panes refuse loudly


def _windowed_agg():
    return connected_components(N_V, merge="gather", codec="dense",
                                windowed=4)


def test_refuses_windowed_with_window_ms():
    src, dst = _zipf_stream()
    with pytest.raises(ValueError, match="window_ms"):
        run_aggregation(_windowed_agg(), _stream(src, dst), window_ms=10)


def test_refuses_windowed_in_fuse():
    with pytest.raises(ValueError, match="windowed"):
        fuse([("cc", _windowed_agg()),
              ("deg", degree_aggregate(N_V, codec="dense"))])


def test_refuses_windowed_in_tenant_tier():
    with pytest.raises(ValueError, match="windowed"):
        _compiled_tenant_plan(_windowed_agg(), 2)


def test_refuses_ttl_without_windowed():
    with pytest.raises(ValueError, match="ttl"):
        connected_components(N_V, codec="compact", ttl_panes=4)


def test_refuses_ttl_shorter_than_window():
    with pytest.raises(ValueError, match="ttl"):
        connected_components(N_V, codec="compact", compact_capacity=N_V,
                             windowed=4, ttl_panes=2)


def test_refuses_ttl_on_dense_codec():
    with pytest.raises(ValueError, match="compact"):
        connected_components(N_V, codec="dense", windowed=4, ttl_panes=4)


def test_refuses_ttl_with_pipeline_lookahead():
    src, dst = _zipf_stream()
    agg = connected_components(N_V, codec="compact", compact_capacity=N_V,
                               windowed=4, ttl_panes=4)
    with pytest.raises(ValueError, match="prefetch|lookahead"):
        run_aggregation(agg, _stream(src, dst), merge_every=2,
                        prefetch_depth=2, h2d_depth=2)


# ---------------------------------------------------------------------- #
# kill -9 mid-pane with units in flight (house crash-child pattern)

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_windows_crash_child.py")


def _spawn(ckpt, out, sleep_s):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt), str(out), str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.faults
def test_windowed_kill9_resume_bit_identical(tmp_path):
    from gelly_tpu.engine.checkpoint import load_checkpoint

    ckpt = tmp_path / "win-ck.npz"
    out_clean = tmp_path / "clean.npz"
    out_resumed = tmp_path / "resumed.npz"

    p = _spawn(tmp_path / "clean-ck.npz", out_clean, 0.0)
    assert p.wait(timeout=300) == 0

    # Throttled run: SIGKILL once a pane-boundary checkpoint is durable
    # — the pipeline guarantees units in flight past the position.
    p = _spawn(ckpt, out_resumed, 0.05)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if p.poll() is not None:
            pytest.fail(f"child exited early (rc={p.returncode})")
        if ckpt.exists():
            break
        time.sleep(0.02)
    else:
        pytest.fail("no checkpoint appeared before the deadline")
    os.kill(p.pid, signal.SIGKILL)
    assert p.wait(timeout=60) == -signal.SIGKILL
    assert not out_resumed.exists()

    _, pos, meta = load_checkpoint(str(ckpt))
    sys.path.insert(0, os.path.dirname(CHILD))
    import _windows_crash_child as child

    total = -(-child.N_EDGES // child.CHUNK)
    assert 0 < pos < total  # mid-stream position
    assert meta.get("windowed") == child.WINDOW
    assert 0 < meta.get("ring_live", 0) <= child.WINDOW

    p = _spawn(ckpt, out_resumed, 0.0)
    assert p.wait(timeout=300) == 0
    resumed, _, _ = load_checkpoint(str(out_resumed))
    clean, _, _ = load_checkpoint(str(out_clean))
    assert len(resumed) == len(clean) == 2
    # Windowed labels AND total pane count, bit for bit.
    assert resumed[0].tobytes() == clean[0].tobytes()
    assert resumed[1].tobytes() == clean[1].tobytes()
