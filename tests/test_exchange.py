"""Vertex-hash all_to_all exchange (the keyBy shuffle) and its consumers.

The reference's keyBy(0) co-locates a vertex's edges on one subtask
(M/SimpleEdgeStream.java:492, M/example/DegreeDistribution.java:56-58);
repartition_by_key is the TPU form. These tests assert the three contracts
VERDICT r1 asked for: every device receives only keys it owns, the entry
multiset is preserved (overflow counted, never silent), and the keyed
consumers (ShardedDegrees exchange mode, ShardedSnapshotStream) match their
host/single-device oracles on the 8-virtual-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.parallel import mesh as mesh_lib, partition
from gelly_tpu.parallel.sharded_window import sharded_slice

N_V = 64
S = 8


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.make_mesh(S)


def _exchange(mesh, key, pay, valid, bucket):
    def body(k, p, v):
        k2, p2, v2, dropped = partition.repartition_by_key(
            k[0], p[0], v[0], S, bucket
        )
        return k2[None], p2[None], v2[None], dropped[None]

    f = mesh_lib.shard_map_fn(
        mesh, body, in_specs=(P("shards"),) * 3, out_specs=(P("shards"),) * 4
    )
    return [np.asarray(x) for x in jax.jit(f)(key, pay, valid)]


def test_exchange_ownership_and_conservation(mesh):
    rng = np.random.default_rng(0)
    L = 16
    key = rng.integers(0, N_V, (S, L)).astype(np.int32)
    pay = rng.integers(0, 100, (S, L)).astype(np.int32)
    valid = rng.random((S, L)) < 0.9
    bucket = partition.default_bucket_capacity(L, S, 3.0)
    k2, p2, v2, dropped = _exchange(mesh, key, pay, valid, bucket)
    assert dropped.tolist() == [0] * S
    for d in range(S):
        got = k2[d][v2[d].astype(bool)]
        # Every received key is owned by this device (striped ownership):
        # the keyBy contract.
        assert (got % S == d).all()
    sent = sorted(zip(key[valid].tolist(), pay[valid].tolist()))
    recv = sorted(
        zip(k2[v2.astype(bool)].tolist(), p2[v2.astype(bool)].tolist())
    )
    assert sent == recv


def test_exchange_overflow_counted_not_silent(mesh):
    # All keys target shard 0 with bucket capacity 1: most entries must be
    # counted as dropped, and received + dropped == sent.
    key = np.zeros((S, 8), np.int32)
    pay = np.arange(S * 8, dtype=np.int32).reshape(S, 8)
    valid = np.ones((S, 8), bool)
    k2, p2, v2, dropped = _exchange(mesh, key, pay, valid, bucket=1)
    assert int(v2.sum()) + int(dropped[0]) == S * 8
    assert int(dropped[0]) > 0


def _stream(src, dst, ts=None, chunk_size=32, val=None):
    kw = {}
    if ts is not None:
        kw.update(timestamps=ts, time=TimeCharacteristic.EVENT)
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, val=val, chunk_size=chunk_size,
                        table=IdentityVertexTable(N_V), **kw),
        N_V,
    )


def test_sharded_degrees_exchange_mode(mesh):
    rng = np.random.default_rng(1)
    src = rng.integers(0, N_V, 500).astype(np.int64)
    dst = rng.integers(0, N_V, 500).astype(np.int64)
    from gelly_tpu.library.degrees import sharded_degrees

    got = sharded_degrees(_stream(src, dst), mesh=mesh,
                          mode="exchange").final_degrees()
    want: dict[int, int] = {}
    for u, v in zip(src.tolist(), dst.tolist()):
        want[u] = want.get(u, 0) + 1
        want[v] = want.get(v, 0) + 1
    assert got == want


def test_sharded_degrees_modes_agree(mesh):
    rng = np.random.default_rng(2)
    src = rng.integers(0, N_V, 300).astype(np.int64)
    dst = rng.integers(0, N_V, 300).astype(np.int64)
    from gelly_tpu.library.degrees import sharded_degrees

    a = sharded_degrees(_stream(src, dst), mesh=mesh,
                        mode="exchange").final_degrees()
    b = sharded_degrees(_stream(src, dst), mesh=mesh,
                        mode="broadcast").final_degrees()
    assert a == b


def test_sharded_degrees_auto_fallback_on_skew(mesh):
    # Star graph: every endpoint buckets to vertex 0's owner. Auto mode
    # must replay overflowed chunks via broadcast and stay correct.
    from gelly_tpu.library.degrees import sharded_degrees

    # Chunks large enough that the per-destination bucket (floor 64) is
    # smaller than one device's worst-case fan-in to vertex 0's owner.
    n = 2048
    src = np.zeros(n, np.int64)
    dst = (np.arange(n) % (N_V - 1) + 1).astype(np.int64)
    sd = sharded_degrees(_stream(src, dst, chunk_size=1024), mesh=mesh,
                         mode="auto", bucket_slack=1.0)
    got = sd.final_degrees()
    want: dict[int, int] = {}
    for u, v in zip(src.tolist(), dst.tolist()):
        want[u] = want.get(u, 0) + 1
        want[v] = want.get(v, 0) + 1
    assert got == want
    assert sd.stats["fallback_chunks"] > 0

    # Strict mode on the same stream raises instead.
    sd2 = sharded_degrees(_stream(src, dst, chunk_size=1024), mesh=mesh,
                          mode="exchange", bucket_slack=1.0)
    with pytest.raises(ValueError, match="overflowed"):
        sd2.final_degrees()


def test_sharded_window_reduce_matches_single_device(mesh):
    rng = np.random.default_rng(3)
    n = 400
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    val = rng.integers(1, 10, n).astype(np.float64)
    ts = np.sort(rng.integers(0, 4000, n)).astype(np.int64)

    def collect(updates):
        out = {}
        for upd in updates:
            ok = np.asarray(upd.valid).astype(bool)
            keys = np.asarray(upd.slots)[ok]
            vals = np.asarray(upd.values)[ok]
            out[upd.window] = dict(zip(keys.tolist(), vals.tolist()))
        return out

    for direction in ("out", "in", "all"):
        sh = sharded_slice(
            _stream(src, dst, ts=ts, val=val), 1000, direction,
            window_capacity=2 * n, mesh=mesh,
        ).reduce_on_edges(jnp.minimum)
        single = _stream(src, dst, ts=ts, val=val).slice(
            1000, direction, window_capacity=2 * n
        ).reduce_on_edges(jnp.minimum)
        assert collect(sh) == collect(single), direction


def test_sharded_window_overflow_raises(mesh):
    # Everything lands on one vertex => one device's buffer takes all the
    # edges; a tiny global capacity must raise, not truncate.
    n = 256
    src = np.zeros(n, np.int64)
    dst = np.ones(n, np.int64)
    ts = np.zeros(n, np.int64)
    sh = sharded_slice(_stream(src, dst, ts=ts, chunk_size=16), 1000, "out",
                       window_capacity=32, mesh=mesh, bucket_slack=1.0)
    with pytest.raises(ValueError, match="overflow|bucket"):
        for _ in sh.reduce_on_edges(jnp.minimum):
            pass


def test_sharded_window_fold_matches_single_device(mesh):
    # fold_neighbors on the mesh: exact per-edge fold-order parity with the
    # single-device segmented scan (VERDICT r2 item 5).
    rng = np.random.default_rng(5)
    n = 400
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    val = rng.integers(1, 10, n).astype(np.float64)
    ts = np.sort(rng.integers(0, 4000, n)).astype(np.int64)

    def fold_fn(acc, key, nbr, v):
        return acc * 0.5 + v  # order-sensitive: exercises fold sequencing

    def collect(updates):
        out = {}
        for upd in updates:
            ok = np.asarray(upd.valid).astype(bool)
            keys = np.asarray(upd.slots)[ok]
            vals = np.asarray(upd.values)[ok]
            out[upd.window] = dict(zip(keys.tolist(),
                                       np.round(vals, 9).tolist()))
        return out

    for direction in ("out", "in", "all"):
        sh = sharded_slice(
            _stream(src, dst, ts=ts, val=val), 1000, direction,
            window_capacity=2 * n, mesh=mesh,
        ).fold_neighbors(0.0, fold_fn)
        single = _stream(src, dst, ts=ts, val=val).slice(
            1000, direction, window_capacity=2 * n
        ).fold_neighbors(0.0, fold_fn)
        assert collect(sh) == collect(single), direction


def test_sharded_window_apply_matches_single_device(mesh):
    # apply_on_neighbors on the mesh: per-device UDF over local views; the
    # per-window edge-count sums across devices equal the single-device
    # count.
    rng = np.random.default_rng(6)
    n = 300
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    ts = np.sort(rng.integers(0, 3000, n)).astype(np.int64)

    def udf(view):
        return jnp.sum(view.valid.astype(jnp.int32))

    sh = dict(
        (w, int(np.asarray(out).sum()))
        for w, out in sharded_slice(
            _stream(src, dst, ts=ts), 1000, "out",
            window_capacity=2 * n, mesh=mesh,
        ).apply_on_neighbors(udf)
    )
    single = dict(
        (w, int(out))
        for w, out in _stream(src, dst, ts=ts).slice(
            1000, "out", window_capacity=2 * n
        ).apply_on_neighbors(udf)
    )
    assert sh == single


def test_sharded_window_triangles_match_single_device(mesh):
    from gelly_tpu.library.triangles import (
        sharded_window_triangles,
        window_triangles,
    )

    rng = np.random.default_rng(7)
    n = 600
    src = rng.integers(0, N_V, n).astype(np.int64)
    dst = rng.integers(0, N_V, n).astype(np.int64)
    # Duplicate a slice of edges so per-device dedup is exercised.
    src[50:100] = src[:50]
    dst[50:100] = dst[:50]
    ts = np.sort(rng.integers(0, 4000, n)).astype(np.int64)

    sharded = {
        w: int(c) for w, c in sharded_window_triangles(
            _stream(src, dst, ts=ts), 1000,
            window_capacity=4 * n, mesh=mesh,
        )
    }
    single = {
        w: int(c) for w, c in window_triangles(
            _stream(src, dst, ts=ts), 1000, window_capacity=4 * n,
        )
    }
    assert sharded == single and sum(single.values()) > 0
