"""Subprocess body for the multi-tenant WIRE kill -9 test
(test_tenant_wire.py) — the exactly-once contract across the full
stack: one ``tenant_streams`` server, N per-tenant sequence spaces,
checkpoint-gated per-tenant acks.

Runs a :class:`MultiTenantEngine` (per-tenant checkpoints, resume=True)
behind a :class:`TenantRouter` with ``checkpoint_acks=True`` and an
``auto_ack=False`` ``tenant_streams`` :class:`IngestServer`: a tenant's
wire ACK fires only after its own CheckpointManager rotation made the
position durable. A SIGKILL at ANY point can therefore never
double-fold an acked chunk — the restarted incarnation re-admits every
tenant at its newest valid checkpoint, the re-attach seeds the
per-tenant wire positions from those resume points, and the
reconnecting client replays exactly each tenant's unacked suffix. The
tier folds DEGREES (pure counting — non-idempotent), so the parent's
bit-identity assertion is sharp: one duplicated or dropped chunk
doubles or loses counts.

argv: <ckpt_dir> <port_file> <out_npz> <total_chunks_per_tenant>
     [framing: plain|stacked]
Env: GELLY_QOS_TENANTS / _NV / _CHUNK override the shape.

``framing=stacked`` asserts the server really staged STACKED frames —
the parent drives a coalescing (``stack=3``) client, so whole
single-tenant stacks ride the TenantRouter as one unit each and the
checkpoint-gated acks land at frame granularity; the engine pipeline
is otherwise IDENTICAL, which is the point.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

TENANTS = int(os.environ.get("GELLY_QOS_TENANTS", "3"))
N_V = int(os.environ.get("GELLY_QOS_NV", "96"))
CHUNK = int(os.environ.get("GELLY_QOS_CHUNK", "16"))


def main(argv):
    ckpt_dir, port_file, out_path = argv[0], argv[1], argv[2]
    total = int(argv[3])
    stacked = len(argv) > 4 and argv[4] == "stacked"

    from gelly_tpu.engine.checkpoint import save_checkpoint
    from gelly_tpu.engine.tenants import MultiTenantEngine
    from gelly_tpu.ingest import IngestServer, TenantRouter
    from gelly_tpu.library.degrees import degree_aggregate

    eng = MultiTenantEngine(
        merge_every=2, checkpoint_dir=ckpt_dir, checkpoint_every=1,
        resume=True,
    )
    eng.add_tier("deg", degree_aggregate(N_V, ingest_combine=False),
                 CHUNK)
    # Pre-admit BEFORE attach: resume=True reloads each tenant's newest
    # checkpoint here, and attach() then seeds the per-tenant wire
    # positions from those resume points — the restarted server
    # re-welcomes every tenant at its durable position.
    for tid in range(TENANTS):
        eng.admit(tid, "deg")
    srv = IngestServer(auto_ack=False, tenant_streams=True,
                       queue_depth=16).start()
    router = TenantRouter(eng, "deg", vertex_capacity=N_V,
                          checkpoint_acks=True)
    eng.start()
    router.attach(srv)
    # Publish the port only once the router is attached (frames staged
    # before attach would ride the default watermark key).
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(srv.port))
    os.replace(tmp, port_file)

    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if all(eng.position(t) >= total for t in range(TENANTS)):
                break
            time.sleep(0.02)
        for tid in range(TENANTS):
            eng.finish(tid)
        while time.time() < deadline:
            tel = eng.telemetry()
            if all(tel[str(t)]["done"] for t in range(TENANTS)):
                break
            time.sleep(0.02)
        # One idle scheduler round flushes the final partial windows
        # (and fires their checkpoint-gated acks).
        time.sleep(0.5)
        rows = [np.asarray(eng.degree(t)) for t in range(TENANTS)]
        positions = [eng.position(t) for t in range(TENANTS)]
        if stacked:
            # Prove the stacked path was really on the wire (a client
            # that silently degraded to per-chunk frames would make
            # this run vacuous).
            from gelly_tpu.obs import bus as obs_bus

            assert obs_bus.get_bus().counters.get(
                "ingest.frames_stacked", 0) > 0, (
                "framing=stacked but the server staged no STACKED "
                "frames"
            )
    finally:
        srv.stop()
        router.stop()
        eng.stop()
    save_checkpoint(out_path, rows, position=sum(positions))


if __name__ == "__main__":
    main(sys.argv[1:])
