"""Ingest codec path: host chunk combiner + compressed device fold.

The codec is the TPU analog of the reference's per-partition partial fold
(M/SummaryBulkAggregation.java:76-80) relocated to the ingest side of the
host->device link. These tests assert exact component parity between the
codec path, the plain chunk-fold path, and a host oracle — single-shard,
batched single-shard, and on the 8-virtual-device mesh.
"""

import numpy as np
import pytest

import jax

from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.library.connected_components import (
    cc_labels_numpy,
    connected_components,
    labels_to_components,
)
from gelly_tpu.parallel import mesh as mesh_lib

N_V = 64


def _rand_edges(n_e=500, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, N_V, n_e).astype(np.int64),
            rng.integers(0, N_V, n_e).astype(np.int64))


def _stream(src, dst, chunk_size=64):
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, chunk_size=chunk_size,
                        table=IdentityVertexTable(N_V)),
        N_V,
    )


def _host_components(src, dst):
    parent = {}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for u, v in zip(src.tolist(), dst.tolist()):
        parent.setdefault(u, u)
        parent.setdefault(v, v)
        ru, rv = find(u), find(v)
        if ru != rv:
            parent[max(ru, rv)] = min(ru, rv)
    comps = {}
    for x in parent:
        comps.setdefault(find(x), set()).add(x)
    return sorted(sorted(c) for c in comps.values())


def _run(merge_every, fold_batch, mesh, ingest_combine=True):
    src, dst = _rand_edges()
    agg = connected_components(N_V, merge="gather",
                               ingest_combine=ingest_combine)
    s = _stream(src, dst)
    labels = s.aggregate(agg, mesh=mesh, merge_every=merge_every,
                         fold_batch=fold_batch).result()
    return labels_to_components(labels, s.ctx), _host_components(src, dst)


def test_codec_single_shard_parity():
    mesh = mesh_lib.make_mesh(1)
    ours, oracle = _run(merge_every=2, fold_batch=1, mesh=mesh)
    assert ours == oracle


def test_codec_batched_single_shard_parity():
    mesh = mesh_lib.make_mesh(1)
    ours, oracle = _run(merge_every=4, fold_batch=4, mesh=mesh)
    assert ours == oracle


def test_codec_mesh_parity():
    # 8 shards, batch = merge_every = 8: payload batch axis splits across
    # the mesh (one chunk forest per device), merged by the collective.
    mesh = mesh_lib.make_mesh(8)
    ours, oracle = _run(merge_every=8, fold_batch=8, mesh=mesh)
    assert ours == oracle


def test_codec_matches_plain_path():
    mesh = mesh_lib.make_mesh(1)
    a, _ = _run(merge_every=4, fold_batch=4, mesh=mesh, ingest_combine=True)
    b, _ = _run(merge_every=4, fold_batch=4, mesh=mesh, ingest_combine=False)
    assert a == b


def test_plain_batched_fold_parity():
    # fold_batch > 1 without a codec: stacked-chunk scan fold (S=1 only).
    mesh = mesh_lib.make_mesh(1)
    ours, oracle = _run(merge_every=4, fold_batch=2, mesh=mesh,
                        ingest_combine=False)
    assert ours == oracle


def test_partial_final_batch():
    # Stream length not divisible by the batch: final group is padded with
    # zero chunks (valid=False) and must not perturb the result.
    src, dst = _rand_edges(n_e=500)  # 500 / 64 -> 7 full chunks + 52 edges
    mesh = mesh_lib.make_mesh(1)
    agg = connected_components(N_V, merge="gather")
    s = _stream(src, dst, chunk_size=64)
    labels = s.aggregate(agg, mesh=mesh, merge_every=4,
                         fold_batch=4).result()
    assert labels_to_components(labels, s.ctx) == _host_components(src, dst)


def test_native_combiner_matches_numpy():
    src, dst = _rand_edges(n_e=2000, seed=3)
    valid = np.ones(src.shape[0], bool)
    valid[::7] = False
    expect = cc_labels_numpy(src.astype(np.int32), dst.astype(np.int32),
                             valid, N_V)
    native = pytest.importorskip("gelly_tpu.utils.native")
    try:
        got = native.cc_chunk_combine(
            src.astype(np.int32), dst.astype(np.int32), valid, N_V
        )
    except Exception:
        pytest.skip("native toolchain unavailable")
    # Both are spanning-forest labels; canonical min-root convention on
    # both sides makes them directly comparable.
    np.testing.assert_array_equal(got, expect)


def test_parity_combiner_matches_numpy():
    from gelly_tpu.library.bipartiteness import parity_labels_numpy

    rng = np.random.default_rng(5)
    # Random bipartite chunk: edges only across the two halves.
    left = rng.integers(0, N_V // 2, 400).astype(np.int32)
    right = (rng.integers(0, N_V // 2, 400) + N_V // 2).astype(np.int32)
    lab_n, par_n, conf_n = parity_labels_numpy(left, right, None, N_V)
    assert not conf_n
    native = pytest.importorskip("gelly_tpu.utils.native")
    try:
        lab_c, par_c, conf_c = native.parity_chunk_combine(
            left, right, None, N_V
        )
    except Exception:
        pytest.skip("native toolchain unavailable")
    np.testing.assert_array_equal(lab_c, lab_n)
    assert not conf_c
    # Parity is unique per component on a bipartite chunk.
    touched = lab_n >= 0
    np.testing.assert_array_equal(par_c[touched], par_n[touched])
    # Odd cycle: both flag conflict.
    tri = np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32)
    assert parity_labels_numpy(*tri, None, N_V)[2]
    assert native.parity_chunk_combine(*tri, None, N_V)[2]


def _bip_result(edges, merge_every, fold_batch, mesh, ingest_combine):
    from gelly_tpu.library.bipartiteness import bipartiteness_check

    src, dst = edges
    agg = bipartiteness_check(N_V, ingest_combine=ingest_combine)
    s = _stream(src, dst, chunk_size=32)
    res = s.aggregate(agg, mesh=mesh, merge_every=merge_every,
                      fold_batch=fold_batch).result()
    colors = np.asarray(res.colors)
    return bool(res.ok), np.asarray(res.labels), colors


def test_bipartiteness_codec_parity():
    rng = np.random.default_rng(9)
    left = rng.integers(0, N_V // 2, 256).astype(np.int64)
    right = (rng.integers(0, N_V // 2, 256) + N_V // 2).astype(np.int64)
    mesh = mesh_lib.make_mesh(1)
    ok_c, lab_c, col_c = _bip_result((left, right), 4, 4, mesh, True)
    ok_p, lab_p, col_p = _bip_result((left, right), 4, 4, mesh, False)
    assert ok_c and ok_p
    np.testing.assert_array_equal(lab_c, lab_p)
    # Colorings may differ by a global flip per component; check edge
    # constraints instead.
    assert (col_c[left] ^ col_c[right]).all()

    # Odd cycle anywhere in the stream flips ok on both paths.
    src = np.concatenate([left, [1, 2, 3]])
    dst = np.concatenate([right, [2, 3, 1]])
    assert not _bip_result((src, dst), 4, 4, mesh, True)[0]
    assert not _bip_result((src, dst), 4, 4, mesh, False)[0]


def test_bipartiteness_codec_mesh():
    rng = np.random.default_rng(11)
    left = rng.integers(0, N_V // 2, 256).astype(np.int64)
    right = (rng.integers(0, N_V // 2, 256) + N_V // 2).astype(np.int64)
    mesh = mesh_lib.make_mesh(8)
    ok, lab, col = _bip_result((left, right), 8, 8, mesh, True)
    assert ok
    assert (col[left] ^ col[right]).all()


def test_codec_soak_scale_parity():
    # VERDICT r1 weak #8 (tiny test scale): a few-hundred-thousand-edge
    # Zipf-skewed stream through the full codec pipeline (native combiner,
    # batching, windows) against the vectorized host oracle.
    rng = np.random.default_rng(13)
    n_v = 1 << 14
    n_e = 300_000
    src = (rng.zipf(1.3, n_e) % n_v).astype(np.int64)
    dst = (rng.zipf(1.3, n_e) % n_v).astype(np.int64)

    mesh = mesh_lib.make_mesh(1)
    agg = connected_components(n_v, merge="gather")
    s = edge_stream_from_source(
        EdgeChunkSource(src, dst, chunk_size=1 << 15,
                        table=IdentityVertexTable(n_v)),
        n_v,
    )
    emissions = list(s.aggregate(agg, mesh=mesh, merge_every=4,
                                 fold_batch=4))
    assert len(emissions) == 3  # ceil(10 chunks / 4)
    got = labels_to_components(emissions[-1], s.ctx)

    from gelly_tpu.library.connected_components import merge_chunk_forest

    glob = np.arange(n_v, dtype=np.int32)
    seen = np.zeros(n_v, bool)
    for lo in range(0, n_e, 1 << 15):
        lab = cc_labels_numpy(src[lo:lo + (1 << 15)].astype(np.int32),
                              dst[lo:lo + (1 << 15)].astype(np.int32),
                              None, n_v)
        seen |= lab >= 0
        glob = merge_chunk_forest(glob, lab)
    comps: dict[int, list[int]] = {}
    for s_ in np.nonzero(seen)[0].tolist():
        comps.setdefault(int(glob[s_]), []).append(s_)
    assert got == sorted(sorted(c) for c in comps.values())


def test_codec_emission_cadence():
    # Window-per-merge_every emission contract survives batching: the
    # stream emits ceil(chunks / merge_every) summaries.
    src, dst = _rand_edges(n_e=512)
    mesh = mesh_lib.make_mesh(1)
    agg = connected_components(N_V, merge="gather")
    s = _stream(src, dst, chunk_size=64)  # 8 chunks
    out = list(s.aggregate(agg, mesh=mesh, merge_every=2, fold_batch=2))
    assert len(out) == 4


# ---------------- degree codec (degrees.degree_aggregate) ---------------- #


@pytest.mark.parametrize("with_deletions", [False, True])
@pytest.mark.parametrize("count_out,count_in",
                         [(True, True), (True, False), (False, True)])
def test_degree_codec_parity(with_deletions, count_out, count_in):
    """Codec path (host bincount deltas — incl. the insertion-only integer
    fast path), plain device fold, and a dict oracle must all agree, with
    partial final chunks and (optionally) deletion events in the mix."""
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(5)
    n_e = 300  # chunk_size 64 -> partial final chunk
    src = rng.integers(0, N_V, n_e).astype(np.int64)
    dst = rng.integers(0, N_V, n_e).astype(np.int64)
    ev = np.zeros(n_e, np.int32)
    if with_deletions:
        # Delete a subset of earlier insertions (degrees may go negative on
        # unmatched deletes; the oracle mirrors that semantics exactly).
        ev[rng.random(n_e) < 0.2] = 1

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, events=ev, chunk_size=64,
                            table=IdentityVertexTable(N_V)),
            N_V,
        )

    oracle = np.zeros(N_V, np.int64)
    sign = np.where(ev == 1, -1, 1)
    if count_out:
        np.add.at(oracle, src, sign)
    if count_in:
        np.add.at(oracle, dst, sign)

    for ingest_combine, fold_batch in [(True, 1), (True, 4), (False, 1)]:
        agg = degree_aggregate(N_V, count_out=count_out, count_in=count_in,
                               ingest_combine=ingest_combine)
        got = np.asarray(stream().aggregate(
            agg, merge_every=4, fold_batch=fold_batch
        ).result())
        assert (got == oracle).all(), (ingest_combine, fold_batch)


def test_plain_batched_fold_mesh_parity():
    # VERDICT r2 item 7: fold_many on the sharded raw path — K chunks per
    # device dispatch, ~K x fewer fold dispatches, identical labels.
    from gelly_tpu.utils.metrics import StageTimer

    mesh = mesh_lib.make_mesh(8)
    src, dst = _rand_edges()  # 500 edges, chunk 64 -> 8 chunks
    agg = connected_components(N_V, merge="gather", ingest_combine=False)
    s = _stream(src, dst)
    timer = StageTimer()
    labels = s.aggregate(agg, mesh=mesh, merge_every=4, fold_batch=4,
                         timer=timer).result()
    assert labels_to_components(labels, s.ctx) == _host_components(src, dst)
    assert timer.counts["fold_dispatch"] == 2  # 8 chunks / batch of 4
