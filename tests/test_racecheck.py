"""gelly_tpu.analysis.racecheck: concurrency race detector + protocol
invariants.

Every RC rule is exercised BOTH ways — a fixture module that must flag
(line-anchored, including the historical SpanTracer deque-iteration and
unlocked-RMW bug classes) and a clean fixture covering the
shadowing/suppression edge cases (lock held via a private helper,
``list()`` snapshot, same-named attribute in an unthreaded class,
condition-wait on the held condition) that must produce zero findings.
The PI invariants are proven clean on repo tip and each single seeded
violation of a scratch ``coordination.py`` flips the CLI exit code
non-zero (ISSUE 8 acceptance)."""

import json
import os
import shutil
import textwrap

import pytest

from gelly_tpu.analysis import racecheck
from gelly_tpu.analysis.__main__ import main as analysis_main

pytestmark = pytest.mark.racecheck

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
COORDINATION = os.path.join(REPO, "gelly_tpu", "engine", "coordination.py")


def _lint_src(tmp_path, src, name="fixture_mod.py"):
    p = tmp_path / name
    p.write_text(src)
    return racecheck.lint_paths(str(tmp_path), [str(p)])


def _line_of(src, marker):
    for i, line in enumerate(src.splitlines(), 1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


# --------------------------------------------------------------------- #
# repo tip

def test_racecheck_clean_on_repo_tip():
    findings = racecheck.lint_paths(REPO, [os.path.join(REPO, "gelly_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_repo_tip_discovers_the_real_thread_roots():
    # The clean result above is vacuous if discovery saw no threads: the
    # checker must find the runtime's actual roots — the checkpoint
    # writer and watchdog daemons, the lease-beat thread, the prefetch
    # worker/submitter, and the pipeline's codec-worker bodies.
    c = racecheck.RaceChecker(REPO)
    c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    names = {r.entry.name for r in c.roots}
    assert {"writer", "run", "_beat_loop", "worker", "submitter",
            "stage_unit"} <= names
    # The ingest subsystem's threads (ISSUE 9): the server accept loop
    # and per-connection handler, the client's rx loop, and the sharded
    # source's per-shard reader bodies (both the Thread-target `reader`
    # in stage_units and the scan `drain`) must all be discovered —
    # the RC-clean gate over gelly_tpu/ is vacuous for them otherwise.
    assert {"_accept_loop", "_conn_loop", "_reader_loop", "reader",
            "drain"} <= names
    # The multi-tenant engine's scheduler thread and the ingest tenant
    # router's per-server drain thread (ISSUE 10): both mutate shared
    # tenant tables/queues/snapshots, so the RC-clean gate must be
    # looking at them.
    assert {"_drive_loop", "_drain_loop"} <= names
    assert any(r.daemon for r in c.roots)
    # and the cross-class typed descent reached LeaseBoard through
    # Coordinator._beat_loop -> self.board.beat()
    assert any(key[1] == "LeaseBoard" for key, _ in c.accesses.items()
               for key in [key[0]])


# --------------------------------------------------------------------- #
# rule fixtures: every rule must flag, line-anchored

RACY_SRC = textwrap.dedent('''\
    import queue
    import threading

    from gelly_tpu.engine.checkpoint import save_checkpoint


    class Pipeline:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self.items = []
            self.inbox = queue.Queue()
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                self.items.append(self.inbox.get())
                self.count = self.count + 1          # M-RMW-ROOT

        def set_zero(self):
            self.count = 0                           # M-RC001

        def bump(self):
            self.count += 1                          # M-RC002

        def snapshot(self):
            return [x for x in self.items]           # M-RC003

        def drain_locked(self):
            with self._lock:
                return self.inbox.get()              # M-RC004


    class Ordered:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def one(self):
            with self.a:
                with self.b:
                    pass

        def two(self):
            with self.b:
                with self.a:                         # M-RC005
                    pass


    def spawn_checkpointer(path, state):
        def writer():
            save_checkpoint(path, state, position=0)  # M-RC006
        t = threading.Thread(target=writer, daemon=True)
        t.start()
''')


def test_flags_every_rule_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, RACY_SRC)
    got = {(f.rule, f.line) for f in findings}
    expected = {
        ("RC002", _line_of(RACY_SRC, "M-RMW-ROOT")),
        ("RC001", _line_of(RACY_SRC, "M-RC001")),
        ("RC002", _line_of(RACY_SRC, "M-RC002")),
        ("RC003", _line_of(RACY_SRC, "M-RC003")),
        ("RC004", _line_of(RACY_SRC, "M-RC004")),
        ("RC005", _line_of(RACY_SRC, "M-RC005")),
        ("RC006", _line_of(RACY_SRC, "M-RC006")),
    }
    assert got == expected, "\n".join(f.render() for f in findings)
    # findings carry real anchors and hints
    for f in findings:
        assert f.path.endswith("fixture_mod.py") and f.line > 0 and f.hint


# --------------------------------------------------------------------- #
# historical bug classes, reproduced as fixtures (ISSUE 8 acceptance)

TRACER_BUG_SRC = textwrap.dedent('''\
    import threading
    from collections import deque


    class MiniTracer:
        """The PR-5 SpanTracer bug: comprehension over the LIVE deque
        while worker threads append raises "deque mutated during
        iteration"."""

        def __init__(self):
            self._ring = deque(maxlen=64)
            self._t = threading.Thread(target=self._worker, daemon=True)
            self._t.start()

        def _worker(self):
            while True:
                self._ring.append({"ph": "X"})

        def spans(self):
            return [r for r in self._ring if r["ph"] == "X"]  # M-BUG
''')


def test_spantracer_deque_iteration_bug_class_flags(tmp_path):
    findings = _lint_src(tmp_path, TRACER_BUG_SRC)
    assert [(f.rule, f.line) for f in findings] \
        == [("RC003", _line_of(TRACER_BUG_SRC, "M-BUG"))]


def test_spantracer_fix_shape_is_clean(tmp_path):
    fixed = TRACER_BUG_SRC.replace(
        "[r for r in self._ring if", "[r for r in list(self._ring) if"
    )
    assert _lint_src(tmp_path, fixed) == []


RMW_BUG_SRC = textwrap.dedent('''\
    import threading


    class AsyncWriter:
        """The CheckpointManager.consecutive_failures shape: a daemon
        writer and the driver's flush() both bump an unlocked counter —
        concurrent bumps lose updates."""

        def __init__(self):
            self.failures = 0

        def save(self, write):
            def writer():
                try:
                    write()
                except Exception:
                    self.failures += 1               # M-RMW-worker
            threading.Thread(target=writer, daemon=True).start()

        def flush(self):
            self.failures += 1                       # M-RMW-flush
''')


def test_unlocked_rmw_bug_class_flags_both_sides(tmp_path):
    findings = _lint_src(tmp_path, RMW_BUG_SRC)
    got = {(f.rule, f.line) for f in findings}
    assert got == {
        ("RC002", _line_of(RMW_BUG_SRC, "M-RMW-worker")),
        ("RC002", _line_of(RMW_BUG_SRC, "M-RMW-flush")),
    }


# --------------------------------------------------------------------- #
# the clean fixture: edge cases that must NOT flag

CLEAN_SRC = textwrap.dedent('''\
    import queue
    import threading


    class SafePipeline:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition()
            self.count = 0
            self.items = []
            self.inbox = queue.Queue()
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                item = self.inbox.get()      # blocking, but no lock held
                with self._lock:
                    self.items.append(item)
                    self.count += 1          # RMW under the lock

        def bump(self):
            with self._lock:
                self._bump_locked()

        def _bump_locked(self):
            self.count += 1                  # lock held via helper

        def snapshot(self):
            return [x for x in list(self.items)]   # list() snapshot

        def wait_ready(self, seq):
            with self._cv:
                self._cv.wait_for(lambda: True)    # wait on HELD cv


    class Unthreaded:
        """Same-named attribute, no thread roots: never shared."""

        def __init__(self):
            self.count = 0
            self.items = []

        def bump(self):
            self.count += 1

        def walk(self):
            return [x for x in self.items]
''')


def test_clean_fixture_produces_zero_findings(tmp_path):
    findings = _lint_src(tmp_path, CLEAN_SRC)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_public_helper_gets_no_lock_floor(tmp_path):
    # The helper discipline is for PRIVATE helpers only: a public method
    # called under a lock somewhere may still be called bare by external
    # code, so its unlocked shared write stays flagged.
    src = CLEAN_SRC.replace("_bump_locked", "bump_locked")
    findings = _lint_src(tmp_path, src)
    assert {(f.rule, f.line) for f in findings} \
        == {("RC002", _line_of(src, "lock held via helper"))}


# --------------------------------------------------------------------- #
# suppression

def test_suppression_silences_one_rule(tmp_path):
    src = RACY_SRC.replace(
        "self.count += 1                          # M-RC002",
        "self.count += 1  # graphlint: disable=RC002",
    )
    findings = _lint_src(tmp_path, src)
    rules_lines = {(f.rule, f.line) for f in findings}
    assert ("RC002", _line_of(src, "disable=RC002")) not in rules_lines
    assert any(r == "RC001" for r, _ in rules_lines)  # others survive


def test_suppression_all_and_wrong_rule(tmp_path):
    src = RACY_SRC.replace(
        "self.count = 0                           # M-RC001",
        "self.count = 0  # graphlint: disable=all",
    )
    findings = _lint_src(tmp_path, src)
    assert not any(f.rule == "RC001" for f in findings)
    # a suppression naming a DIFFERENT rule does not silence the line
    src2 = RACY_SRC.replace(
        "self.count = 0                           # M-RC001",
        "self.count = 0  # graphlint: disable=RC006",
    )
    findings2 = _lint_src(tmp_path, src2)
    assert any(f.rule == "RC001" for f in findings2)


# --------------------------------------------------------------------- #
# protocol invariants (coordination.py)

def test_invariants_clean_on_repo_tip():
    findings = racecheck.check_invariants(COORDINATION)
    assert findings == [], "\n".join(f.render() for f in findings)


_PI_SEEDS = {
    "PI001": (
        "\n\ndef _rogue_commit(coord, epoch, position):\n"
        "    return coord.store.commit(epoch, position, 1)\n"
    ),
    "PI002": (
        "\n\ndef _rogue_epoch(self):\n"
        "    self._next_epoch = 7\n"
    ),
    "PI003": (
        "\n\ndef _rogue_intent(store, epoch, host, position):\n"
        "    store.write_intent(epoch, host, position)\n"
    ),
    "PI004": (
        "\n\ndef _rogue_beat(board):\n"
        "    write_json_atomic(board._path(board.host), {})\n"
    ),
}


@pytest.mark.parametrize("rule", sorted(_PI_SEEDS))
def test_seeded_invariant_violation_turns_exit_nonzero(tmp_path, rule,
                                                       capsys):
    """ISSUE 8 acceptance: seeding any single protocol-invariant
    violation into a scratch copy of coordination.py flips the racecheck
    exit code non-zero."""
    scratch = tmp_path / "scratch"
    scratch.mkdir()
    dst = scratch / "coordination.py"
    shutil.copy(COORDINATION, dst)
    # the unmodified scratch copy is clean (race rules + invariants)
    assert analysis_main(["racecheck", str(scratch),
                          "--root", REPO]) == 0
    capsys.readouterr()
    dst.write_text(dst.read_text() + _PI_SEEDS[rule])
    findings = racecheck.lint_paths(REPO, [str(scratch)])
    assert [f.rule for f in findings] == [rule], \
        "\n".join(f.render() for f in findings)
    assert analysis_main(["racecheck", str(scratch),
                          "--root", REPO]) == 1
    out = capsys.readouterr()
    assert rule in out.out


def test_invariant_suppression_honored(tmp_path):
    scratch = tmp_path / "s"
    scratch.mkdir()
    dst = scratch / "coordination.py"
    shutil.copy(COORDINATION, dst)
    dst.write_text(
        dst.read_text()
        + "\n\ndef _rogue_epoch(self):\n"
          "    self._next_epoch = 7  # graphlint: disable=PI002\n"
    )
    assert racecheck.lint_paths(REPO, [str(scratch)]) == []


# --------------------------------------------------------------------- #
# CLI exit-code contract

def test_cli_racecheck_subcommand_exit_zero_on_tip(capsys):
    rc = analysis_main(["racecheck", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "racecheck: 0 finding(s)" in out
    assert "analysis clean (racecheck)" in out


@pytest.mark.slow  # tier-1 budget: racecheck lane; subcommand smoke stays
def test_cli_all_prints_per_tool_summary(capsys):
    rc = analysis_main(["--all", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    for tool in ("abi", "jitlint", "racecheck", "contracts",
                 "plancheck", "liveness"):
        assert f"{tool}: 0 finding(s)" in out
    assert ("analysis clean (abi, jitlint, racecheck, contracts, "
            "plancheck, liveness, suppressions-audit)") in out


def test_cli_nonzero_and_counts_on_findings(tmp_path, capsys):
    (tmp_path / "racy.py").write_text(RACY_SRC)
    rc = analysis_main(["racecheck", str(tmp_path), "--root", REPO])
    captured = capsys.readouterr()
    assert rc == 1
    assert "RC001" in captured.out
    assert "racecheck: 7 finding(s)" in captured.err


@pytest.mark.slow  # tier-1 budget: racecheck lane; subcommand smoke stays
def test_cli_json_format(tmp_path, capsys):
    (tmp_path / "racy.py").write_text(RACY_SRC)
    rc = analysis_main(["racecheck", str(tmp_path), "--root", REPO,
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["ok"] is False
    assert payload["total"] == payload["tools"]["racecheck"]["count"] == 7
    f0 = payload["tools"]["racecheck"]["findings"][0]
    assert {"path", "line", "rule", "message", "hint"} <= set(f0)
    # clean run: ok true, every tool present under --all
    rc2 = analysis_main(["--all", "--root", REPO, "--format=json"])
    payload2 = json.loads(capsys.readouterr().out)
    assert rc2 == 0 and payload2["ok"] is True
    assert set(payload2["tools"]) == {"abi", "jitlint", "racecheck",
                                      "contracts", "plancheck",
                                      "liveness"}


def test_cli_list_rules_includes_rc_and_pi(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("RC001", "RC006", "PI001", "PI004", "GL001", "AB001"):
        assert rid in out
