"""Subprocess body for the windowed pane-ring kill -9 crash test
(test_windows.py).

Runs the FULL pipelined engine path over a windowed compact CC plan —
codec workers, double-buffered H2D, donated folds, pane-ring closes with
checkpoints at pane boundaries — throttled so the kill lands mid-pane
with units in flight past the recorded position. The second incarnation
resumes (``resume=True`` once the checkpoint exists) and must reproduce
the unkilled run exactly: same pane count, final windowed labels
bit-identical — proving one checkpoint position covers the ring, the
pane index, and the compact-id session together.

argv: <checkpoint_path> <out_npz> [emit_sleep_seconds]
Env: GELLY_WIN_EDGES / _NV / _CHUNK override the stream shape.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gelly_tpu import edge_stream_from_edges  # noqa: E402
from gelly_tpu.engine.checkpoint import save_checkpoint  # noqa: E402
from gelly_tpu.library.connected_components import (  # noqa: E402
    connected_components,
)

N_EDGES = int(os.environ.get("GELLY_WIN_EDGES", "2048"))
N_V = int(os.environ.get("GELLY_WIN_NV", "128"))
CHUNK = int(os.environ.get("GELLY_WIN_CHUNK", "32"))
WINDOW = 4  # panes per sliding window; pane = merge_every chunks


def build_stream():
    rng = np.random.default_rng(13)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    return edge_stream_from_edges(
        [(int(a), int(b)) for a, b in pairs],
        vertex_capacity=N_V, chunk_size=CHUNK,
    )


def main(argv):
    ckpt_path, out_path = argv[0], argv[1]
    sleep_s = float(argv[2]) if len(argv) > 2 else 0.0
    stream = build_stream()
    agg = connected_components(N_V, merge="gather", codec="compact",
                               compact_capacity=N_V, windowed=WINDOW)
    res = stream.aggregate(
        agg, merge_every=2,
        checkpoint_path=ckpt_path, checkpoint_every=1,
        resume=os.path.exists(ckpt_path),
        codec_workers=2, h2d_depth=2,
    )
    labels = None
    for labels in res:
        if sleep_s:
            # Throttled consumer: compress/H2D stages run ahead, so the
            # parent's SIGKILL lands mid-pane with units in flight.
            time.sleep(sleep_s)
    save_checkpoint(
        out_path,
        [np.asarray(labels), np.asarray([res.stats["windows_closed"]])],
        position=res.stats["chunks"])


if __name__ == "__main__":
    main(sys.argv[1:])
