"""gelly_tpu.analysis.liveness: liveness & progress checker.

Every LV rule is exercised BOTH ways — a seeded-violation fixture that
must flag (line-anchored) and a clean fixture proving the rule's
exemption paths (stop-flag headers, timeout-poll idioms, unguarded
tail flushes, teardown drops, bounded handoffs, the swap-to-local
close idiom). The three historical bug classes are re-seeded verbatim
and each flips the CLI exit code: the PR 8 batched-ack tail (LV203),
the PR 10 stranded ``pipeline.staged_depth`` gauge (LV202), and the
PR 14 coordinated-checkpoint ledger leak (LV302). The tip audit's one
real finding — IngestServer ingress-stamping its wire watermark ledger
with no exit in the class — has a static red/green pair here plus a
behavioral regression (stamp, stop, assert no stranded backlog) in the
server section. Satellites ride along: the suppression audit
(SUP001/002/003, tokenized inventory, the ``suppressions`` gate vs the
``--all`` warning lane), ``--format=sarif``, and the loader's
mtime/size cache invalidation."""

import json
import os
import textwrap

import pytest

from gelly_tpu.analysis import jitlint, liveness, loader, suppressions
from gelly_tpu.analysis.__main__ import main as analysis_main

pytestmark = pytest.mark.liveness

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _lint_files(tmp_path, files):
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        if isinstance(src, bytes):
            p.write_bytes(src)
        else:
            p.write_text(src)
        paths.append(str(p))
    return liveness.lint_paths(str(tmp_path), paths)


def _lint_src(tmp_path, src, name="fixture_mod.py"):
    return _lint_files(tmp_path, {name: src})


def _line_of(src, marker):
    for i, line in enumerate(src.splitlines(), 1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


def _rules(findings):
    return [(f.rule, f.line) for f in findings]


# --------------------------------------------------------------------- #
# repo tip (ISSUE 16 acceptance: zero unsuppressed findings, and the
# root discovery the tip-clean assertion rests on is not vacuous)

def test_liveness_clean_on_repo_tip():
    findings = liveness.lint_paths(REPO, [os.path.join(REPO, "gelly_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_tip_root_closure_not_vacuous():
    # Tip-clean proves nothing if no thread root was discovered or the
    # reachability closure stayed empty: the checker must be walking
    # the real serving-plane loops (ingest accept/conn, tenant drive,
    # router drain, checkpoint writer).
    c = liveness.LivenessChecker(REPO)
    c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    assert len(c._rc.roots) >= 10
    assert len(c._reach) >= len(c._rc.roots)
    reached_files = {os.path.basename(m.path)
                     for m, _c, _f, _s, _r in c._reach.values()}
    assert {"server.py", "tenants.py", "resilience.py"} <= reached_files


# --------------------------------------------------------------------- #
# LV101: root-reachable while-True with no exit path

LV101_FLAG = textwrap.dedent('''\
    import threading

    class Poller:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def stop(self):
            self._t.join()

        def _run(self):
            while True:  # anchor-101
                self._tick()

        def _tick(self):
            pass
''')


def test_lv101_flags_unterminated_root_loop(tmp_path):
    findings = _lint_src(tmp_path, LV101_FLAG)
    assert _rules(findings) == [("LV101", _line_of(LV101_FLAG,
                                                   "anchor-101"))]


def test_lv101_clean_on_stop_flag_header_and_break(tmp_path):
    src = textwrap.dedent('''\
        import threading

        class Poller:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
                threading.Thread(target=self._drain, daemon=True).start()

            def stop(self):
                self._stop.set()
                self._t.join()

            def _run(self):
                while not self._stop.is_set():
                    self._tick()

            def _drain(self):
                while True:
                    if self._stop.is_set():
                        break
                    self._tick()

            def _tick(self):
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv101_ignores_unreachable_and_generator_loops(tmp_path):
    # A while-True in a function no thread root reaches is main-thread
    # code (its caller bounds it); a generator's while-True is driven
    # and closeable by its consumer.
    src = textwrap.dedent('''\
        def batches(q):
            while True:
                yield q.popleft()

        def spin_forever():
            while True:
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv101_break_in_nested_loop_is_not_a_witness(tmp_path):
    # The break belongs to the inner for — the outer while-True still
    # has no exit.
    src = textwrap.dedent('''\
        import threading

        class Worker:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def stop(self):
                self._stop = True

            def _run(self):
                while True:  # anchor-101
                    for item in self._items:
                        if item is None:
                            break
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [("LV101", _line_of(src, "anchor-101"))]


# --------------------------------------------------------------------- #
# LV102: untimed blocking call in a root-reachable loop

LV102_FLAG = textwrap.dedent('''\
    import threading

    class Consumer:
        def start(self):
            threading.Thread(target=self._drain, daemon=True).start()

        def stop(self):
            self._stop.set()

        def _drain(self):
            while not self._stop.is_set():
                item = self._q.get()  # anchor-102
                self._handle(item)

        def _handle(self, item):
            pass
''')


def test_lv102_flags_untimed_get(tmp_path):
    findings = _lint_src(tmp_path, LV102_FLAG)
    assert _rules(findings) == [("LV102", _line_of(LV102_FLAG,
                                                   "anchor-102"))]


def test_lv102_clean_on_timeout_poll_idioms(tmp_path):
    # The three vetted idioms: a timeout= kwarg, an except-timeout
    # guard around a bare recv, and a component-scope settimeout
    # covering accept.
    src = textwrap.dedent('''\
        import queue
        import socket
        import threading

        class Consumer:
            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()
                threading.Thread(target=self._recv_loop,
                                 daemon=True).start()
                threading.Thread(target=self._accept_loop,
                                 daemon=True).start()

            def stop(self):
                self._stop.set()

            def _drain(self):
                while not self._stop.is_set():
                    try:
                        item = self._q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    self._handle(item)

            def _recv_loop(self):
                while not self._stop.is_set():
                    try:
                        data = self._sock.recv(4096)
                    except socket.timeout:
                        continue
                    self._handle(data)

            def _accept_loop(self):
                self._listener.settimeout(0.1)
                while not self._stop.is_set():
                    try:
                        conn, _ = self._listener.accept()
                    except socket.timeout:
                        continue
                    self._handle(conn)

            def _handle(self, item):
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV201: PAUSE emitted without a reachable RESUME

LV201_FLAG = textwrap.dedent('''\
    PAUSE = 6
    RESUME = 7

    class Throttle:
        def apply(self, sock):
            sock.sendall(pack(PAUSE, 0))  # anchor-201
''')


def test_lv201_flags_pause_without_resume(tmp_path):
    findings = _lint_src(tmp_path, LV201_FLAG)
    assert _rules(findings) == [("LV201", _line_of(LV201_FLAG,
                                                   "anchor-201"))]


def test_lv201_clean_when_component_resumes(tmp_path):
    src = textwrap.dedent('''\
        PAUSE = 6
        RESUME = 7

        class Throttle:
            def apply(self, sock):
                sock.sendall(pack(PAUSE, 0))
                try:
                    self._wait_drained()
                finally:
                    sock.sendall(pack(RESUME, 0))

            def _wait_drained(self):
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV202: polled gauge with no background publisher (the PR 10 class)

LV202_FLAG = textwrap.dedent('''\
    import time

    class Backpressure:
        def submit(self, item):
            self._stage(item)
            self.bus.gauge("pipeline.staged_depth", self.depth)

        def wait_drained(self):
            while self.bus.gauges.get("pipeline.staged_depth", 0) > self.low:  # anchor-202
                time.sleep(0.01)

        def _stage(self, item):
            pass
''')


def test_lv202_flags_submit_path_only_gauge(tmp_path):
    # The PR 10 bug verbatim: the RESUME condition polls a gauge only
    # the submit path re-publishes — once submission stops the poll
    # spins forever.
    findings = _lint_src(tmp_path, LV202_FLAG)
    assert _rules(findings) == [("LV202", _line_of(LV202_FLAG,
                                                   "anchor-202"))]
    assert "submit path" in findings[0].message


def test_lv202_flags_never_published_gauge(tmp_path):
    src = textwrap.dedent('''\
        class Waiter:
            def wait(self):
                while self.bus.gauges.get("ghost.depth", 0) > 0:  # anchor-202
                    pass
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [("LV202", _line_of(src, "anchor-202"))]
    assert "never published" in findings[0].message


def test_lv202_clean_with_root_reachable_publisher(tmp_path):
    src = LV202_FLAG + textwrap.dedent('''\

        class Drainer:
            def start(self):
                import threading
                threading.Thread(target=self._drain, daemon=True).start()

            def stop(self):
                self._stop.set()

            def _drain(self):
                while not self._stop.is_set():
                    self.bus.gauge("pipeline.staged_depth",
                                   self.q.qsize())
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv202_clean_with_enqueue_hook_lambda_publisher(tmp_path):
    # The aggregation idiom: the gauge hook is a lambda handed to the
    # prefetch plumbing — published from the worker side, not a loop
    # the closure scan can see, so closures count as background.
    src = textwrap.dedent('''\
        class Pipe:
            def build(self):
                return make_stage(
                    gauge=lambda d: self.bus.gauge("pipe.depth", d))

            def wait(self):
                while self.bus.gauges.get("pipe.depth", 0) > self.low:
                    pass
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV203: accumulator flushed only under its threshold (the PR 8 class)

LV203_FLAG = textwrap.dedent('''\
    import threading

    class AckServer:
        def start(self):
            threading.Thread(target=self._conn_loop, daemon=True).start()

        def stop(self):
            self._stop.set()

        def _conn_loop(self, sock):
            pending = []  # anchor-203
            while not self._stop.is_set():
                seq = self._read(sock)
                pending.append(seq)
                if len(pending) >= self.ack_every:
                    self._send_ack(sock, pending)
                    pending = []

        def _read(self, sock):
            return 0

        def _send_ack(self, sock, seqs):
            pass
''')


def test_lv203_flags_threshold_only_flush(tmp_path):
    # The PR 8 bug verbatim: acks batch up and flush only at
    # ack_every — a stream going idle below the threshold strands the
    # tail and the client's flush() hangs forever.
    findings = _lint_src(tmp_path, LV203_FLAG)
    assert _rules(findings) == [("LV203", _line_of(LV203_FLAG,
                                                   "anchor-203"))]


def test_lv203_clean_with_tail_flush_after_loop(tmp_path):
    src = LV203_FLAG.replace(
        "    def _read(self, sock):",
        "        if pending:\n"
        "            self._send_ack(sock, pending)\n"
        "\n"
        "    def _read(self, sock):", 1)
    assert "if pending:" in src
    assert _lint_src(tmp_path, src) == []


def test_lv203_clean_with_idle_hook_flush(tmp_path):
    # The tip's actual fix shape: an unguarded flush in a nested idle
    # hook (`if pending:` is a presence test, not a threshold guard).
    src = textwrap.dedent('''\
        import threading

        class AckServer:
            def start(self):
                threading.Thread(target=self._conn_loop,
                                 daemon=True).start()

            def stop(self):
                self._stop.set()

            def _conn_loop(self, sock):
                pending = [0]

                def flush_tail():
                    if pending[0]:
                        self._send_ack(sock, pending[0])
                        pending[0] = 0

                recv = make_recv(sock, idle=flush_tail)
                while not self._stop.is_set():
                    self._read(recv)
                    pending[0] += 1
                    if pending[0] >= self.ack_every:
                        pending[0] = 0
                        self._send_ack(sock, pending[0])

            def _read(self, recv):
                return 0

            def _send_ack(self, sock, upto):
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV301: ledger enter with no exit in the component (the tip finding)

LV301_FLAG = textwrap.dedent('''\
    class Ingest:
        def on_frame(self, seq):
            self.bus.watermarks.stamp("wire", seq)  # anchor-301
''')


def test_lv301_flags_stamp_without_exit(tmp_path):
    findings = _lint_src(tmp_path, LV301_FLAG)
    assert _rules(findings) == [("LV301", _line_of(LV301_FLAG,
                                                   "anchor-301"))]


def test_lv301_clean_with_teardown_drop(tmp_path):
    # The shape of the tip fix: stop() drops the stream the ingress
    # path stamped.
    src = LV301_FLAG + textwrap.dedent('''\

        def stop(self):
            self.bus.watermarks.drop("wire")
    ''').replace("\n", "\n    ").rstrip() + "\n"
    assert _lint_src(tmp_path, src) == []


def test_lv301_tracks_ledger_alias_and_call_base_chains(tmp_path):
    # `wm = <...>.watermarks` aliases and call-in-chain bases
    # (get_bus().watermarks.stamp) must both resolve — the tip uses
    # both spellings.
    src = textwrap.dedent('''\
        class Runner:
            def setup(self):
                self.wm = None

            def run(self, bus):
                wm = bus.watermarks
                wm.stamp("stream", self.position)  # anchor-301

        class Submitter:
            def submit(self, seq):
                get_bus().watermarks.stamp(str(seq), seq)  # anchor-301b
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [
        ("LV301", _line_of(src, "anchor-301")),
        ("LV301", _line_of(src, "anchor-301b")),
    ]


def test_lv301_retire_fold_is_not_an_exit(tmp_path):
    # retire_fold observes latency but keeps the stamps — a component
    # that only fold-retires still leaks durably.
    src = textwrap.dedent('''\
        class Folder:
            def on_chunk(self, seq):
                self.bus.watermarks.stamp("stream", seq)  # anchor-301

            def on_fold(self, upto):
                self.bus.watermarks.retire_fold("stream", upto)
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [("LV301", _line_of(src, "anchor-301"))]


# --------------------------------------------------------------------- #
# LV302: exit on only one sibling durability branch (the PR 14 class)

LV302_FLAG = textwrap.dedent('''\
    class Runner:
        def _maybe_checkpoint(self):
            if self.coordinator is None:
                self._checkpoint_local()
            else:
                self._checkpoint_coordinated()  # anchor-302

        def _checkpoint_local(self):
            save_checkpoint(self.path)
            self._retire()

        def _checkpoint_coordinated(self):
            self.coordinator.checkpoint_all(self.path)

        def _retire(self):
            self.bus.watermarks.retire_durable("stream", self.position)
''')


def test_lv302_flags_coordinated_branch_leak(tmp_path):
    # The PR 14 bug verbatim: both dispatch branches publish a
    # checkpoint, only the local one retires the ledger — one stamp
    # leaks per chunk on the coordinated path.
    findings = _lint_src(tmp_path, LV302_FLAG)
    assert _rules(findings) == [("LV302", _line_of(LV302_FLAG,
                                                   "anchor-302"))]


def test_lv302_clean_when_both_branches_retire(tmp_path):
    src = LV302_FLAG.replace(
        "        self.coordinator.checkpoint_all(self.path)",
        "        self.coordinator.checkpoint_all(self.path)\n"
        "        self._retire()")
    assert _lint_src(tmp_path, src) == []


def test_lv302_silent_on_components_without_ledger_calls(tmp_path):
    # An if/else over checkpoint helpers in a component that never
    # touches a ledger is out of scope — no enter/exit to pair.
    src = textwrap.dedent('''\
        class Saver:
            def save(self):
                if self.fast:
                    quick_checkpoint(self.path)
                else:
                    full_checkpoint(self.path)
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV303: pending-map insert with no removal

LV303_FLAG = textwrap.dedent('''\
    class Client:
        def __init__(self):
            self._unacked = {}

        def send(self, seq, frame):
            self._unacked[seq] = frame  # anchor-303
''')


def test_lv303_flags_insert_without_removal(tmp_path):
    findings = _lint_src(tmp_path, LV303_FLAG)
    assert _rules(findings) == [("LV303", _line_of(LV303_FLAG,
                                                   "anchor-303"))]


def test_lv303_clean_with_pop_del_or_clear(tmp_path):
    src = LV303_FLAG + (
        "\n"
        "    def on_ack(self, upto):\n"
        "        for seq in [s for s in self._unacked if s < upto]:\n"
        "            del self._unacked[seq]\n")
    assert _lint_src(tmp_path, src) == []


def test_lv303_counter_increment_without_decrement(tmp_path):
    src = textwrap.dedent('''\
        class Tracker:
            def enter(self):
                self._in_flight += 1  # anchor-303

        class Balanced:
            def enter(self):
                self._in_flight += 1

            def leave(self):
                self._in_flight -= 1
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [("LV303", _line_of(src, "anchor-303"))]


def test_lv303_ignores_non_pending_attrs(tmp_path):
    src = textwrap.dedent('''\
        class Cache:
            def put(self, k, v):
                self._memo[k] = v
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV401: thread started with no reachable join/stop signal

LV401_FLAG = textwrap.dedent('''\
    import threading

    class Heart:
        def start(self):
            t = threading.Thread(target=self._beat, daemon=True)  # anchor-401
            t.start()

        def _beat(self):
            while not self._running:
                self._pulse()

        def _pulse(self):
            pass
''')


def test_lv401_flags_unstoppable_thread(tmp_path):
    findings = _lint_src(tmp_path, LV401_FLAG)
    assert _rules(findings) == [("LV401", _line_of(LV401_FLAG,
                                                   "anchor-401"))]


def test_lv401_clean_with_stop_flag_write(tmp_path):
    src = LV401_FLAG + (
        "\n"
        "    def stop(self):\n"
        "        self._running = False\n")
    assert _lint_src(tmp_path, src) == []


def test_lv401_clean_on_event_set_and_join(tmp_path):
    src = textwrap.dedent('''\
        import threading

        class Board:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def close(self):
                self._stop.set()
                self._t.join(timeout=1.0)

            def _run(self):
                while not self._stop.is_set():
                    self._tick()

            def _tick(self):
                pass
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv401_bounded_handoff_is_exempt(tmp_path):
    # The Watchdog idiom: the spawning function awaits the worker with
    # a timeout and deliberately abandons it on expiry — the spawn is
    # bounded by its caller, not a daemon needing a stop path.
    src = textwrap.dedent('''\
        import threading

        class Watchdog:
            def call(self, fn):
                done = threading.Event()
                t = threading.Thread(target=lambda: self._run(fn, done),
                                     daemon=True)
                t.start()
                if not done.wait(self.timeout):
                    raise TimeoutError("stalled")

            def _run(self, fn, done):
                fn()
                done.set()
    ''')
    assert _lint_src(tmp_path, src) == []


# --------------------------------------------------------------------- #
# LV402: socket/file on self with no close path

LV402_FLAG = textwrap.dedent('''\
    import socket

    class Listener:
        def start(self):
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # anchor-402
            self._sock.bind((self.host, 0))
''')


def test_lv402_flags_unclosed_socket_attr(tmp_path):
    findings = _lint_src(tmp_path, LV402_FLAG)
    assert _rules(findings) == [("LV402", _line_of(LV402_FLAG,
                                                   "anchor-402"))]


def test_lv402_clean_on_direct_close_and_helper_pass(tmp_path):
    src = textwrap.dedent('''\
        import socket

        def _close_quietly(sock):
            try:
                sock.close()
            except OSError:
                pass

        class Listener:
            def start(self):
                self._sock = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
                self._conn = socket.create_connection(self.addr)

            def stop(self):
                self._sock.close()
                _close_quietly(self._conn)
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv402_swap_to_local_close_idiom_is_clean(tmp_path):
    # The IngestClient teardown shape: the attribute is swapped into a
    # local under the lock, then the local is closed.
    src = textwrap.dedent('''\
        import socket

        class Client:
            def connect(self):
                self._sock = socket.create_connection(self.addr)

            def close(self):
                with self._lock:
                    sock, self._sock = self._sock, None
                if sock is not None:
                    sock.close()
    ''')
    assert _lint_src(tmp_path, src) == []


def test_lv402_open_via_local_then_self_assign(tmp_path):
    # The IngestServer start() shape: opened into a local, configured,
    # then published onto self — still an open site.
    src = textwrap.dedent('''\
        import socket

        class Server:
            def start(self):
                ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                ls.bind((self.host, 0))
                self._listener = ls  # anchor-402
    ''')
    findings = _lint_src(tmp_path, src)
    assert _rules(findings) == [("LV402", _line_of(src, "anchor-402"))]


# --------------------------------------------------------------------- #
# suppression scoping

def test_lv_suppression_is_line_and_rule_scoped(tmp_path):
    suppressed_src = LV301_FLAG.replace(
        "  # anchor-301",
        "  # graphlint: disable=LV301 -- exit lives in the router")
    assert _lint_src(tmp_path, suppressed_src) == []
    wrong_rule = LV301_FLAG.replace(
        "  # anchor-301",
        "  # graphlint: disable=LV101 -- wrong rule, must not mask")
    assert [f.rule for f in _lint_src(tmp_path, wrong_rule)] == ["LV301"]


# --------------------------------------------------------------------- #
# the three historical bug classes flip the CLI exit code

@pytest.mark.parametrize("src,rule", [
    (LV203_FLAG, "LV203"),   # PR 8: batched-ack tail never flushed
    (LV202_FLAG, "LV202"),   # PR 10: stranded pause-gauge
    (LV302_FLAG, "LV302"),   # PR 14: coordinated-path ledger leak
], ids=["pr8-ack-tail", "pr10-stranded-gauge", "pr14-ledger-leak"])
def test_historical_bug_classes_flip_cli_exit_code(tmp_path, src, rule,
                                                   capsys):
    (tmp_path / "seeded.py").write_text(src)
    rc = analysis_main(["liveness", str(tmp_path), "--root", REPO])
    captured = capsys.readouterr()
    assert rc == 1
    assert rule in captured.out
    assert "liveness: 1 finding(s)" in captured.err


def test_tip_fix_regression_server_drops_wire_ledger_on_stop():
    # Red/green for the tip audit's real finding: IngestServer ingress-
    # stamps its wire watermark ledger; before the fix nothing in the
    # class ever retired it, so staged-but-unconsumed frames read as
    # permanently growing backlog after stop(). Green: stop() drops
    # the stream.
    from gelly_tpu import obs
    from gelly_tpu.ingest.server import IngestServer

    with obs.scope() as bus, obs.record_metrics():
        srv = IngestServer(port=0)
        try:
            bus.watermarks.stamp(srv.watermark_stream, 0)
            assert bus.watermarks.snapshot()[
                srv.watermark_stream]["pending"] == 1
        finally:
            srv.stop()
        assert srv.watermark_stream not in bus.watermarks.snapshot()
        assert bus.watermarks.max_backlog_age() == 0.0


# --------------------------------------------------------------------- #
# CLI surface

def test_cli_liveness_subcommand_exit_zero_on_tip(capsys):
    rc = analysis_main(["liveness", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "liveness: 0 finding(s)" in out
    assert "analysis clean (liveness)" in out


@pytest.mark.slow  # tier-1 budget: liveness lane; subcommand smoke stays
def test_cli_skip_liveness(capsys):
    rc = analysis_main(["--all", "--root", REPO, "--skip-liveness",
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert "liveness" not in payload["tools"]
    assert set(payload["tools"]) == {"abi", "jitlint", "racecheck",
                                     "contracts", "plancheck"}


def test_cli_json_format_carries_liveness_findings(tmp_path, capsys):
    (tmp_path / "seeded.py").write_text(LV301_FLAG)
    rc = analysis_main(["liveness", str(tmp_path), "--root", REPO,
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["ok"] is False
    assert payload["tools"]["liveness"]["count"] == 1
    f0 = payload["tools"]["liveness"]["findings"][0]
    assert f0["rule"] == "LV301"
    assert f0["line"] == _line_of(LV301_FLAG, "anchor-301")


def test_cli_github_format_annotates_liveness(tmp_path, capsys):
    (tmp_path / "seeded.py").write_text(LV101_FLAG)
    rc = analysis_main(["liveness", str(tmp_path), "--root", REPO,
                        "--format=github"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=" in out and "title=LV101" in out


def test_cli_list_rules_includes_lv_and_sup(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("LV101", "LV102", "LV201", "LV202", "LV203", "LV301",
                "LV302", "LV303", "LV401", "LV402", "SUP001", "SUP002",
                "SUP003"):
        assert rid in out


def test_unparseable_file_is_loud_from_liveness(tmp_path):
    findings = _lint_src(tmp_path, "def broken(:\n", name="bad.py")
    assert [f.rule for f in findings] == ["SRC001"]


# --------------------------------------------------------------------- #
# satellite: --format=sarif

def test_sarif_document_shape_and_rule_metadata(tmp_path, capsys):
    (tmp_path / "seeded.py").write_text(LV301_FLAG)
    rc = analysis_main(["liveness", str(tmp_path), "--root", REPO,
                        "--format=sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "gelly-analysis"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    # one run carries the metadata of EVERY tool's rules
    for rid in ("AB001", "GL001", "RC001", "PI001", "EO001", "WP001",
                "OB001", "PC101", "LV101", "SUP001", "SRC001"):
        assert rid in rule_ids
    (res,) = run["results"]
    assert res["ruleId"] == "LV301" and res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == _line_of(LV301_FLAG,
                                                 "anchor-301")
    assert loc["artifactLocation"]["uri"].endswith("seeded.py")


def test_sarif_clean_tip_has_no_results(capsys):
    rc = analysis_main(["liveness", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO, "--format=sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["runs"][0]["results"] == []


# --------------------------------------------------------------------- #
# satellite: suppression audit

def _audit_files(tmp_path, files):
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    return suppressions.audit(str(tmp_path),
                              [str(tmp_path / n) for n in files])


def test_sup001_justification_required(tmp_path):
    bare = LV301_FLAG.replace("  # anchor-301",
                              "  # graphlint: disable=LV301")
    findings = _audit_files(tmp_path, {"mod.py": bare})
    assert [f.rule for f in findings] == ["SUP001"]


def test_sup001_accepts_trailing_and_preceding_justifications(tmp_path):
    trailing = LV301_FLAG.replace(
        "  # anchor-301",
        "  # graphlint: disable=LV301 -- the router owns the exit")
    preceding = LV301_FLAG.replace(
        "        self.bus.watermarks.stamp(\"wire\", seq)  # anchor-301",
        "        # Vetted: the exit lives in the attached router's\n"
        "        # drain loop, outside this component.\n"
        "        self.bus.watermarks.stamp(\"wire\", seq)"
        "  # graphlint: disable=LV301")
    assert _audit_files(tmp_path, {"a.py": trailing}) == []
    assert _audit_files(tmp_path, {"b.py": preceding}) == []


def test_sup002_stale_suppression_flagged(tmp_path):
    # The directive names a rule that does NOT fire on this line any
    # more — it must be reported stale, not silently kept.
    src = textwrap.dedent('''\
        class Quiet:
            def fine(self):
                return 1  # graphlint: disable=LV301 -- was needed once
    ''')
    findings = _audit_files(tmp_path, {"mod.py": src})
    assert [f.rule for f in findings] == ["SUP002"]


def test_sup002_live_suppression_not_stale(tmp_path):
    live = LV301_FLAG.replace(
        "  # anchor-301",
        "  # graphlint: disable=LV301 -- the router owns the exit")
    assert _audit_files(tmp_path, {"mod.py": live}) == []


def test_sup003_unknown_rule_id(tmp_path):
    src = textwrap.dedent('''\
        x = 1  # graphlint: disable=LV999 -- typo that masks nothing
    ''')
    findings = _audit_files(tmp_path, {"mod.py": src})
    assert [f.rule for f in findings] == ["SUP003"]


def test_inventory_ignores_docstring_mentions(tmp_path):
    # Every analysis module's docstring QUOTES the directive syntax —
    # the inventory tokenizes, so string literals are not directives.
    src = textwrap.dedent('''\
        """Suppress with ``# graphlint: disable=LVxxx`` on the line."""
        HELP = "use # graphlint: disable=RC001 to vet an exception"
    ''')
    (tmp_path / "doc.py").write_text(src)
    assert suppressions.inventory([str(tmp_path / "doc.py")]) == []
    assert _audit_files(tmp_path, {"doc2.py": src}) == []


def test_ignoring_suppressions_restores_flag_on_error():
    assert jitlint._IGNORE_SUPPRESSIONS is False
    with pytest.raises(RuntimeError):
        with suppressions.ignoring_suppressions():
            assert jitlint._IGNORE_SUPPRESSIONS is True
            raise RuntimeError("boom")
    assert jitlint._IGNORE_SUPPRESSIONS is False


def test_cli_suppressions_gate_exit_code(tmp_path, capsys):
    bare = LV301_FLAG.replace("  # anchor-301",
                              "  # graphlint: disable=LV301")
    (tmp_path / "mod.py").write_text(bare)
    rc = analysis_main(["suppressions", str(tmp_path), "--root", REPO])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SUP001" in captured.out
    assert "suppressions: 1 finding(s)" in captured.err


def test_cli_suppressions_gate_clean_on_tip(capsys):
    rc = analysis_main(["suppressions",
                        os.path.join(REPO, "gelly_tpu"), "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "analysis clean (suppressions)" in out


def test_tip_audit_is_not_vacuous():
    # The tip-clean gate above must actually be exercising directives:
    # the package carries vetted suppressions, and their rules still
    # fire when directives are ignored (else SUP002 would flag).
    inv = suppressions.inventory([os.path.join(REPO, "gelly_tpu")])
    assert len(inv) >= 2
    rules = {r for _p, _l, rs, _m, _ls in inv for r in rs}
    assert {"RC006", "EO004"} <= rules


def test_cli_all_reports_suppression_warnings_without_rc_flip(tmp_path,
                                                              capsys):
    # Under --all the audit is a warning lane: visible, never the exit
    # code (the dedicated subcommand is the gate).
    bare = textwrap.dedent('''\
        x = 1  # graphlint: disable=LV999
    ''')
    (tmp_path / "mod.py").write_text(bare)
    rc = analysis_main(["--all", str(tmp_path), "--root", REPO,
                        "--skip-abi", "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True
    sup = payload["suppressions"]
    assert sup["count"] >= 1
    assert any(f["rule"] == "SUP003" for f in sup["findings"])


# --------------------------------------------------------------------- #
# satellite: loader mtime/size cache invalidation

def test_loader_reparses_edited_file(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("x = 1\n")
    cache = loader.SourceCache()
    first = cache.get(str(p))
    assert first is not None and "x = 1" in first.src
    # Same content length, different content — mtime must invalidate.
    p.write_text("y = 2\n")
    os.utime(p, ns=(os.stat(p).st_atime_ns,
                    os.stat(p).st_mtime_ns + 1_000_000))
    second = cache.get(str(p))
    assert second is not first
    assert "y = 2" in second.src


def test_loader_serves_cached_tree_while_unchanged(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("x = 1\n")
    cache = loader.SourceCache()
    a = cache.get(str(p))
    b = cache.get(str(p))
    assert a is b and a.tree is b.tree


def test_loader_error_entry_invalidated_on_fix(tmp_path):
    # A file cached as unparseable must be re-read once it is fixed on
    # disk — a watch-mode process must not report a stale SRC001.
    p = tmp_path / "mod.py"
    p.write_text("def broken(:\n")
    cache = loader.SourceCache()
    assert cache.get(str(p)) is None
    assert cache.error(str(p)) is not None
    p.write_text("def fixed():\n    return 1\n")
    os.utime(p, ns=(os.stat(p).st_atime_ns,
                    os.stat(p).st_mtime_ns + 1_000_000))
    ms = cache.get(str(p))
    assert ms is not None and "fixed" in ms.src
    assert cache.error(str(p)) is None
