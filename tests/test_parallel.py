"""Parallel-layer tests on the 8-virtual-device CPU mesh (MiniCluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gelly_tpu import make_chunk
from gelly_tpu.parallel import (
    SHARD_AXIS,
    butterfly_merge,
    gather_merge,
    make_mesh,
    num_shards,
    owned_mask,
    psum_tree,
    shard_map_fn,
    split_chunk,
    slots_per_shard,
    to_local_slot,
)


def test_mesh_has_8_virtual_devices():
    mesh = make_mesh()
    assert num_shards(mesh) == 8


def test_split_chunk_roundtrip():
    c = make_chunk(np.arange(16), np.arange(16) + 100, capacity=16)
    s = split_chunk(c, 4)
    assert s.src.shape == (4, 4)
    assert np.asarray(s.src).reshape(-1).tolist() == np.asarray(c.src).tolist()


def test_butterfly_merge_equals_global_reduce():
    mesh = make_mesh()
    S = num_shards(mesh)
    x = jnp.arange(S * 3, dtype=jnp.float32).reshape(S, 3)

    def body(xs):
        local = xs  # [3] per device
        merged = butterfly_merge(jnp.maximum, local, S)
        return merged

    out = shard_map_fn(mesh, body, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS))(x)
    # every device must hold the global max
    expect = np.asarray(x).max(axis=0)
    for d in range(S):
        np.testing.assert_array_equal(np.asarray(out)[d], expect)


def test_butterfly_merge_noncommutative_size_merge():
    # Merge monoid like the reference's CombineCC (smaller into larger):
    # (count, payload_sum) where combine keeps the sum but max-counts;
    # associativity over the butterfly must still yield the global result.
    mesh = make_mesh()
    S = num_shards(mesh)
    counts = jnp.arange(S, dtype=jnp.int32).reshape(S, 1) + 1
    sums = jnp.ones((S, 1), jnp.float32)

    def combine(a, b):
        return (a[0] + b[0], a[1] + b[1])

    def body(c, s):
        merged = butterfly_merge(combine, (c[0], s[0]), S)
        return merged[0][None], merged[1][None]

    c_out, s_out = shard_map_fn(
        mesh, body, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )(counts, sums)
    assert np.asarray(c_out)[0].item() == sum(range(1, S + 1))
    assert np.asarray(s_out)[3].item() == S


def test_gather_merge_stacks_all_shards():
    mesh = make_mesh()
    S = num_shards(mesh)
    x = jnp.arange(S, dtype=jnp.int32).reshape(S, 1)

    def body(xs):
        return gather_merge(lambda st: jnp.sum(st, axis=0), xs)

    out = shard_map_fn(mesh, body, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS))(x)
    assert np.asarray(out).reshape(S).tolist() == [sum(range(S))] * S


def test_psum_tree():
    mesh = make_mesh()
    S = num_shards(mesh)
    x = jnp.ones((S, 4), jnp.int32)

    def body(xs):
        return psum_tree(xs)

    out = shard_map_fn(mesh, body, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS))(x)
    assert np.asarray(out)[0].tolist() == [S] * 4


def test_vertex_range_partition_masks():
    mesh = make_mesh()
    S = num_shards(mesh)
    cap = 64
    per = slots_per_shard(cap, S)
    slots = jnp.arange(cap, dtype=jnp.int32)

    def body():
        m = owned_mask(slots, S)
        return jnp.sum(m.astype(jnp.int32))[None]

    counts = shard_map_fn(mesh, body, in_specs=(), out_specs=P(SHARD_AXIS))()
    assert np.asarray(counts).tolist() == [per] * S
    # Striped ownership: slot s -> shard s % S, local offset s // S.
    assert int(to_local_slot(jnp.int32(3 * S + 5), S)) == 3


def test_hierarchical_merge_degree_invariance():
    # SummaryTreeReduce's degree knob: merging at degree 1/2/4/8 must give
    # identical results (the tree shape changes, the monoid result cannot).
    import jax
    from gelly_tpu.parallel.collectives import (
        butterfly_merge,
        hierarchical_merge,
    )

    mesh = make_mesh()
    S = num_shards(mesh)
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1000, (S, 16)).astype(np.int64)

    def run(degree):
        def body(x):
            if degree is None:
                return butterfly_merge(jnp.add, x[0], S)[None]
            return hierarchical_merge(jnp.add, x[0], S, degree)[None]

        f = shard_map_fn(mesh, body, in_specs=(P(SHARD_AXIS),),
                         out_specs=P(SHARD_AXIS))
        return np.asarray(jax.jit(f)(vals))

    flat = run(None)
    for degree in (1, 2, 4, 8):
        got = run(degree)
        np.testing.assert_array_equal(got, flat)
        # replicated output: every shard holds the global sum
        np.testing.assert_array_equal(got[0], vals.sum(axis=0))


def test_hierarchical_cross_group_pairs_are_leader_only():
    """Structural claim of the hierarchical schedule (VERDICT r4 item 6):
    in the COMPILED program, every collective-permute whose pairs cross a
    phase-1 group boundary (the DCN hops on a multi-host mesh) touches
    ONLY group leaders — cross-group traffic is degree*log2(degree)
    leader payloads, not all-shards. Asserted on the lowered HLO's
    source_target_pairs, not just the Python perm lists."""
    import re

    from gelly_tpu.parallel.collectives import hierarchical_merge

    mesh = make_mesh()
    S = num_shards(mesh)
    degree = 4
    group = S // degree  # leaders = shard index % group == 0

    def body(x):
        return hierarchical_merge(jnp.minimum, x[0], S, degree)[None]

    f = jax.jit(shard_map_fn(mesh, body, in_specs=(P(SHARD_AXIS),),
                             out_specs=P(SHARD_AXIS)))
    x = jnp.arange(S * 4, dtype=jnp.int32).reshape(S, 4)
    hlo = f.lower(x).as_text()
    ops = re.findall(
        r"collective_permute.*?source_target_pairs\s*=\s*dense<\[(.*?)\]>",
        hlo,
    )
    assert ops, "no collective_permute ops found in lowered HLO"
    cross_ops = 0
    for pairs_txt in ops:
        pairs = [
            tuple(int(v) for v in m.groups())
            for m in re.finditer(r"\[(\d+),\s*(\d+)\]", pairs_txt)
        ]
        assert pairs, pairs_txt
        crossing = [
            (a, b) for a, b in pairs if a // group != b // group
        ]
        if crossing:
            cross_ops += 1
            # Every pair in a cross-group op must be leader-to-leader.
            assert all(
                a % group == 0 and b % group == 0 for a, b in pairs
            ), pairs
    # The phase-2 exchange exists: log2(degree) cross-group steps.
    assert cross_ops >= 1, "no cross-group collective found"


def test_cc_tree_degree_knob_parity():
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.library.connected_components import (
        connected_components_tree,
        labels_to_components,
    )

    mesh = make_mesh()
    rng = np.random.default_rng(1)
    src = rng.integers(0, 64, 400).astype(np.int64)
    dst = rng.integers(0, 64, 400).astype(np.int64)

    def run(degree):
        s = edge_stream_from_source(
            EdgeChunkSource(src, dst, chunk_size=64,
                            table=IdentityVertexTable(64)), 64)
        agg = connected_components_tree(64, degree=degree)
        labels = s.aggregate(agg, mesh=mesh, merge_every=2).result()
        return labels_to_components(labels, s.ctx)

    base = run(None)
    assert all(run(d) == base for d in (2, 4, 8))
