"""Subprocess body for the telemetry SIGKILL test (test_telemetry.py)
— the ``_ingest_crash_child.py`` harness pattern applied to the REAL
engine serve path with serving-plane telemetry recording on.

Runs an :class:`~gelly_tpu.ingest.server.IngestServer`
(``auto_ack=False``) feeding ``run_aggregation`` over a DEGREES plan —
the ±1 endpoint scatter is non-idempotent, so a double-folded acked
chunk is visible in the final vector, keeping the parent's
exactly-once assertion sharp. Acks follow durability: a daemon thread
polls the engine checkpoint header and acks its recorded position.

Telemetry under test: ``obs.set_recording(True)`` is on, so the run
records fold-dispatch / checkpoint-write / receive→stage histograms
and the ``"stream"`` e2e watermark ledger — the parent interleaves
STATS requests mid-stream and asserts the JSON. Per closed window the
child samples the backlog age and the oldest pending position into the
output file; the parent asserts no sample is negative or
wall-clock-sized (time travel), and that the RESUMED incarnation's
oldest stamp never falls below the resumed position (the ledger
re-seeds from the checkpoint position, not the wall clock).

argv: <ckpt_path> <port_file> <out_npz> [chunk_sleep_s]
"""

import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_V = 128
CHUNK = 16
MERGE_EVERY = 2


def main(argv):
    ckpt_path, port_file, out_path = argv[0], argv[1], argv[2]
    sleep_s = float(argv[3]) if len(argv) > 3 else 0.0

    from gelly_tpu import obs
    from gelly_tpu.engine.aggregation import run_aggregation
    from gelly_tpu.engine.checkpoint import (
        CheckpointCorruptError,
        read_checkpoint_header,
        save_checkpoint,
    )
    from gelly_tpu.ingest import IngestServer
    from gelly_tpu.library.degrees import degree_aggregate

    obs.set_recording(True)
    bus = obs.get_bus()

    resume = os.path.exists(ckpt_path)
    pos = 0
    if resume:
        pos = int(read_checkpoint_header(ckpt_path)["position"])

    srv = IngestServer(auto_ack=False, resume_seq=pos, queue_depth=8,
                       stop_on_bye=True).start()
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(srv.port))
    os.replace(tmp, port_file)

    # Acks follow durability: poll the (atomically-replaced) engine
    # checkpoint and ack its recorded position — the auto_ack=False
    # half of the exactly-once contract, off the consumer thread so
    # the tail window's ack never deadlocks against the client's
    # flush().
    stop_acker = threading.Event()

    def acker():
        while not stop_acker.is_set():
            if os.path.exists(ckpt_path):
                try:
                    hdr = read_checkpoint_header(ckpt_path)
                    srv.ack(int(hdr["position"]))
                except (CheckpointCorruptError, OSError):
                    pass  # mid-replace; next tick reads the new file
            time.sleep(0.02)

    t_ack = threading.Thread(target=acker, daemon=True)
    t_ack.start()

    agg = degree_aggregate(N_V)
    # The engine's resume skips the first `pos` chunks of the stream —
    # but the WIRE already resumed the sequence (resume_seq), so the
    # socket only re-delivers the unacked suffix. Pad the skipped
    # prefix with placeholders so absolute positions line up; the
    # engine drops them unread (idx <= skip_until) and folds exactly
    # the suffix the client retransmits.
    import itertools

    stream = itertools.chain(
        iter([object()] * pos), srv.chunks(CHUNK, vertex_capacity=N_V)
    )
    res = run_aggregation(
        agg, stream,
        merge_every=MERGE_EVERY, checkpoint_path=ckpt_path,
        checkpoint_every=1, resume=resume,
    )
    ages: list = []
    oldest: list = []
    final = None
    try:
        for final in res:
            if sleep_s:
                time.sleep(sleep_s)
            ages.append(bus.watermarks.backlog_age("stream"))
            op = bus.watermarks.oldest_position("stream")
            oldest.append(-1 if op is None else op)
    finally:
        stop_acker.set()
        srv.stop()
    t_ack.join(timeout=5)
    hdr = read_checkpoint_header(ckpt_path)
    srv.ack(int(hdr["position"]))

    save_checkpoint(
        out_path,
        {
            "degrees": np.asarray(final, dtype=np.int64),
            "ages": np.asarray(ages, dtype=np.float64),
            "oldest": np.asarray(oldest, dtype=np.int64),
        },
        position=int(hdr["position"]),
        meta={"resume_pos": pos, "resumed": resume},
    )


if __name__ == "__main__":
    main(sys.argv[1:])
