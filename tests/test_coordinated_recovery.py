"""Coordinated recovery on a REAL 2-process gloo mesh — the subprocess
proof tier (extends the ``tests/_crash_child.py`` pattern).

Each worker (``tests/_coord_child.py``) joins a jax.distributed cluster
over the loopback coordinator, folds its own edge partition through a
coordinated ``ResilientRunner`` (checkpoint barriers + two-phase commit
into a shared store, cadenced path flatten), and finally merges label
forests across hosts over the mesh. The parent:

1. runs a golden (single-process, shared code) pass for every host's
   expected final local state + the merged forest;
2. starts the pair slowed down, waits for a committed manifest, and
   SIGKILLs one host (leader or follower) MID-WINDOW — the survivor
   observes the lease expiry and dies loudly (bounded, no deadlock);
3. restarts the pair: both hosts must re-join at the barrier-agreed
   manifest position, fold only the remaining chunks, and produce
   final states BIT-IDENTICAL to the golden pass, with the merged
   components matching the single-process numpy oracle.

Both variants are slow-marked — ~20s each of subprocess
jax.distributed bring-up against a tier-1 budget the pre-existing suite
nearly fills — and run on every push in the CI ``multihost`` lane; the
protocol logic itself is tier-1-covered in-process by
``tests/test_coordination.py``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_coord_child.py")

_STREAM = dict(
    GELLY_COORD_EDGES="768", GELLY_COORD_NV="96",
    GELLY_COORD_CHUNK="16", GELLY_COORD_CADENCE="4",
)
# 768 edges / 2 hosts / 16-edge chunks = 24 chunks per host.
CHUNKS_PER_HOST = 24


def _env(**extra):
    env = dict(os.environ, REPO_ROOT=os.path.dirname(
        os.path.dirname(os.path.abspath(CHILD))))
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(_STREAM)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pair(store, out, sleep_s):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in (0, 1):
        env = _env(
            COORD=coord, NPROCS=2, PID_IDX=pid,
            GELLY_COORD_STORE=store, GELLY_COORD_OUT=out,
            GELLY_COORD_SLEEP=sleep_s,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-I", CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    return procs


def _golden(tmp_path):
    out = str(tmp_path / "out.npz")
    env = _env(GELLY_COORD_MODE="golden", GELLY_COORD_OUT=out, NPROCS=2)
    r = subprocess.run(
        [sys.executable, "-I", CHILD], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert r.returncode == 0, f"golden failed\n{r.stdout}\n{r.stderr}"
    assert "COORD_GOLDEN_OK" in r.stdout
    return out


def _load_out(path):
    from gelly_tpu.engine.checkpoint import load_checkpoint

    leaves, position, _ = load_checkpoint(path)
    # dict pytree: leaves in sorted-key order
    keys = ["merged_parent", "merged_seen", "parent", "seen"]
    return dict(zip(keys, leaves)), position


def _comps(parent, seen):
    out = {}
    for v in np.nonzero(seen)[0].tolist():
        r = v
        while parent[r] != r:
            r = parent[r]
        out.setdefault(r, set()).add(v)
    return sorted(sorted(c) for c in out.values())


def _oracle_comps():
    from gelly_tpu.library.connected_components import cc_labels_numpy

    rng = np.random.default_rng(11)
    nv = int(_STREAM["GELLY_COORD_NV"])
    pairs = rng.integers(0, nv, (int(_STREAM["GELLY_COORD_EDGES"]), 2))
    full = cc_labels_numpy(
        pairs[:, 0].astype(np.int64), pairs[:, 1].astype(np.int64),
        None, nv,
    )
    return _comps(np.where(full >= 0, full, np.arange(nv)), full >= 0)


def _wait_manifest(store, min_epoch, timeout=120.0):
    path = os.path.join(store, "MANIFEST.json")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                man = json.load(f)
            if man.get("epoch", 0) >= min_epoch:
                return man
        except (OSError, json.JSONDecodeError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"no manifest at epoch >= {min_epoch} in {store}")


def _drain(procs, timeout=120.0):
    outs = []
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            out, err = p.communicate(timeout=max(1.0, deadline
                                                 - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        outs.append((p.returncode, out, err))
    return outs


def _kill_and_restart(tmp_path, victim):
    """Shared body: crash ``victim`` (0 = leader, 1 = follower)
    mid-stream, restart the pair, verify barrier-position re-join and
    bit-identical finals."""
    golden_out = _golden(tmp_path)
    store = str(tmp_path / "store")
    out = str(tmp_path / "run.npz")

    # Run A: slowed so the kill lands mid-stream, after >= 2 committed
    # barriers (position >= 8 of 24).
    procs = _spawn_pair(store, out, sleep_s=0.15)
    try:
        _wait_manifest(store, min_epoch=2)
        os.kill(procs[victim].pid, signal.SIGKILL)
    except BaseException:
        for p in procs:
            p.kill()
        raise
    outs = _drain(procs)
    # The survivor must die LOUDLY (lease-expiry CoordinationError /
    # distributed teardown), never hang — _drain enforces the bound.
    survivor_rc, _, survivor_err = outs[1 - victim]
    assert survivor_rc != 0, "survivor should abort when its peer dies"
    man = _wait_manifest(store, min_epoch=2)
    resume_pos = man["position"]
    assert 0 < resume_pos < CHUNKS_PER_HOST, (
        f"kill did not land mid-stream (manifest at {resume_pos})"
    )

    # Run B: fresh pair over the same store — re-join and finish fast.
    procs = _spawn_pair(store, out, sleep_s=0.0)
    outs = _drain(procs)
    for rc, stdout, stderr in outs:
        assert rc == 0, f"restarted worker failed\n{stdout}\n{stderr}"
        assert "COORD_OK" in stdout
        # re-entry exactly at the barrier-agreed manifest position,
        # folding only the remainder
        resumed = [ln for ln in stdout.splitlines()
                   if ln.startswith("COORD_RESUMED")][0].split()
        start, folded = int(resumed[1]), int(resumed[2])
        assert start == resume_pos
        assert folded == CHUNKS_PER_HOST - resume_pos > 0

    oracle = _oracle_comps()
    for pid in (0, 1):
        got, pos = _load_out(f"{out}.{pid}")
        want, _ = _load_out(f"{golden_out}.golden{pid}")
        assert pos == CHUNKS_PER_HOST
        # bit-identical local summaries (the acceptance bar)
        assert got["parent"].tobytes() == want["parent"].tobytes()
        assert got["seen"].tobytes() == want["seen"].tobytes()
        # merged components match the single-process oracle
        assert _comps(got["merged_parent"], got["merged_seen"]) == oracle


@pytest.mark.faults
@pytest.mark.slow
def test_gloo_sigkill_leader_midstream_resumes_bit_identical(tmp_path):
    """SIGKILL the LEADER (process 0) mid-window on a live 2-process
    gloo mesh: the restarted pair re-joins at the barrier-agreed
    position and finishes bit-identical to the uninterrupted fold.
    Slow-marked (~20s of subprocess jax.distributed bring-up — the
    tier-1 budget is nearly spent by the pre-existing suite); the CI
    ``multihost`` lane runs it on every push, and the protocol logic it
    exercises is tier-1-covered in-process by test_coordination.py."""
    _kill_and_restart(tmp_path, victim=0)


@pytest.mark.faults
@pytest.mark.slow
def test_gloo_sigkill_follower_midstream_resumes_bit_identical(tmp_path):
    """Same contract with the FOLLOWER (process 1) killed — leadership
    never changes hands, but the leader must abort its next barrier on
    the dead peer's lease and the restart path is identical."""
    _kill_and_restart(tmp_path, victim=1)


@pytest.mark.slow
def test_gloo_per_host_traces_stitch_into_one_timeline(tmp_path):
    """ISSUE 20 acceptance: an uninterrupted 2-process gloo run exports
    one trace ring per host (``GELLY_COORD_TRACE``); ``stitch_traces``
    merges them into a single valid timeline — one pid per host, clocks
    aligned on the first shared ``coordination.barrier_agreed`` epoch,
    and a flow-arrow pair drawn at every shared barrier."""
    from gelly_tpu.obs.export import stitch_traces, validate_chrome_trace

    store = str(tmp_path / "store")
    out = str(tmp_path / "run.npz")
    tprefix = str(tmp_path / "ring")
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in (0, 1):
        env = _env(
            COORD=coord, NPROCS=2, PID_IDX=pid,
            GELLY_COORD_STORE=store, GELLY_COORD_OUT=out,
            GELLY_COORD_SLEEP=0.0, GELLY_COORD_TRACE=tprefix,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-I", CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = _drain(procs)
    for rc, stdout, stderr in outs:
        assert rc == 0, f"worker failed\n{stdout}\n{stderr}"
        assert "COORD_OK" in stdout

    rings = [f"{tprefix}.{pid}.json" for pid in (0, 1)]
    for r in rings:
        with open(r) as f:
            validate_chrome_trace(json.load(f))

    merged_path = str(tmp_path / "stitched.json")
    trace = stitch_traces(rings, out_path=merged_path)
    other = trace["otherData"]
    assert other["stitched_hosts"] == 2
    assert other["barrier_epochs"], "no shared barrier epoch recorded"
    # One pid per host, identity preserved.
    assert {m["host"]["process_index"]
            for m in other["hosts"].values()} == {0, 1}
    pids = {ev["pid"] for ev in trace["traceEvents"]}
    assert pids == {1, 2}
    # The aligned barrier instants coincide per epoch, and every shared
    # epoch drew its "s"/"f" flow pair across hosts.
    flows_s = [ev for ev in trace["traceEvents"] if ev["ph"] == "s"]
    flows_f = [ev for ev in trace["traceEvents"] if ev["ph"] == "f"]
    assert len(flows_s) == len(flows_f) == len(other["barrier_epochs"])
    ep0 = other["barrier_epochs"][0]
    at = [ev["ts"] for ev in trace["traceEvents"]
          if ev.get("name") == "coordination.barrier_agreed"
          and (ev.get("args") or {}).get("epoch") == ep0]
    assert len(at) == 2 and abs(at[0] - at[1]) < 1e-6
    # Round-trips through disk as valid Chrome-trace JSON.
    with open(merged_path) as f:
        validate_chrome_trace(json.load(f))
