"""gelly_tpu.analysis.contracts: durability-contract checker.

Every EO/WP/OB rule is exercised BOTH ways — a seeded-violation fixture
that must flag (line-anchored) and a clean fixture proving the rule's
exemption paths (ack after the durability write, retired-counter
provenance, the tmp+fsync+rename helpers, validate-before-prune, the
CRC-guard-first order, ack-bounded resend trims, the glossary
round-trip including prefixed wildcard names). Suppression scoping and
taint-through-rebind are covered explicitly, the repo tip is asserted
clean (the ISSUE 11 acceptance gate), and each seeded violation flips
the unified CLI exit code non-zero."""

import json
import os
import textwrap

import pytest

from gelly_tpu.analysis import contracts
from gelly_tpu.analysis.__main__ import main as analysis_main

pytestmark = pytest.mark.contracts

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
BUS = os.path.join(REPO, "gelly_tpu", "obs", "bus.py")


def _lint_src(tmp_path, src, name="fixture_mod.py"):
    p = tmp_path / name
    p.write_text(src)
    return contracts.lint_paths(str(tmp_path), [str(p)])


def _lint_files(tmp_path, files):
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        p.write_text(src)
        paths.append(str(p))
    return contracts.lint_paths(str(tmp_path), paths)


def _line_of(src, marker):
    for i, line in enumerate(src.splitlines(), 1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


# --------------------------------------------------------------------- #
# repo tip (ISSUE 11 acceptance: zero unsuppressed findings)

def test_contracts_clean_on_repo_tip():
    findings = contracts.lint_paths(REPO, [os.path.join(REPO, "gelly_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_tip_glossary_covers_the_pr11_audit_drift():
    # The first tip audit of this tool found four names PRs 9/10 grew
    # without documenting (the OB001 drift class); they must stay in
    # the glossary — and stay EMITTED (deleting the call site without
    # deleting the entry is the OB002 half of the same regression).
    with open(BUS) as f:
        lines = f.read().splitlines()
    documented = {m.group(1) for m in
                  (contracts._GLOSSARY_RE.match(ln) for ln in lines) if m}
    drifted = {"engine.dirty_rows_gathered",
               "sharded_cc.window_dirty_max_shard",
               "sharded_cc.emissions_dense",
               "sharded_cc.emissions_sparse"}
    assert drifted <= documented
    c = contracts.ContractChecker(REPO)
    c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    emitted = {s.name for s in c._emits if not s.wildcard}
    assert drifted <= emitted
    # The prefixed metrics families (utils/metrics.py publish helpers)
    # were the wildcard half of the same audit: each family needs at
    # least one representative glossary entry carrying its suffix.
    for sfx in (".busy_s", ".edges", ".edges_per_sec"):
        assert any(g.endswith(sfx) for g in c._glossary), sfx


def test_tip_glossary_parse_and_emit_discovery_not_vacuous():
    # The tip-clean assertion above is vacuous if the OB pass saw no
    # glossary or no call sites: the checker must have parsed the real
    # table and discovered the runtime's emit surface.
    c = contracts.ContractChecker(REPO)
    c.lint_paths([os.path.join(REPO, "gelly_tpu")])
    assert len(c._glossary) > 40
    exact = {s.name for s in c._emits if not s.wildcard}
    assert {"ingest.frames_received", "tenants.dispatches",
            "coordination.committed", "pipeline.staged_depth"} <= exact
    # the prefixed publish_checkpoint names ride the wildcard path
    wild = {s.name for s in c._emits if s.wildcard}
    assert {".checkpoints", ".checkpoint_bytes"} <= wild


# --------------------------------------------------------------------- #
# EO rules: flag side, line-anchored

EO_SRC = textwrap.dedent('''\
    import os

    from gelly_tpu.engine.checkpoint import save_checkpoint


    def consume(server, chunks, ckpt_mgr, state):
        for seq, chunk in chunks:
            state = fold(state, chunk)
            server.ack(seq + 1)                          # M-EO001
        ckpt_mgr.save(state, retired_of(chunks))


    def serve(engine, ckpt_path, state):
        save_checkpoint(ckpt_path, state, position=0)
        return IngestServer(port=0, auto_ack=True)       # M-EO001-AUTO


    class Staging:
        def __init__(self):
            self._next_seq = 0
            self.retired = 0

        def bad_snapshot(self, path, state):
            pos = self._next_seq
            save_checkpoint(path, state, position=pos)   # M-EO002

        def write_manifest(self, store_dir, obj):
            with open(store_dir + "/MANIFEST.json", "w") as f:  # M-EO003
                f.write(str(obj))

        def prune_rotation(self, files, keep):
            for old in files[:-keep]:
                os.unlink(old)                           # M-EO004
''')


def test_eo_rules_flag_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, EO_SRC)
    got = {(f.rule, f.line) for f in findings}
    assert got == {
        ("EO001", _line_of(EO_SRC, "M-EO001")),
        ("EO001", _line_of(EO_SRC, "M-EO001-AUTO")),
        ("EO002", _line_of(EO_SRC, "M-EO002")),
        ("EO003", _line_of(EO_SRC, "M-EO003")),
        ("EO004", _line_of(EO_SRC, "M-EO004")),
    }, "\n".join(f.render() for f in findings)
    for f in findings:
        assert f.path.endswith("fixture_mod.py") and f.line > 0 and f.hint


def test_eo002_taints_through_rebinds(tmp_path):
    # The GL006 alias discipline: one (or two) rebinds between the
    # staged counter and the position argument must not launder it.
    src = textwrap.dedent('''\
        from gelly_tpu.engine.checkpoint import save_checkpoint


        class S:
            def snap(self, path, state):
                staged_count = self._pending_chunks
                pos = staged_count
                save_checkpoint(path, state, position=pos)   # M
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("EO002", _line_of(src, "# M"))]
    assert "pending" in findings[0].message or "staged" in findings[0].message


def test_eo002_transitive_chase_respects_binding_order(tmp_path):
    # `pos` captured `retired` BEFORE retired was rebound to the staged
    # counter: per-edge flow sensitivity must resolve `retired` at the
    # line where `pos` read it, not at the call line.
    src = textwrap.dedent('''\
        from gelly_tpu.engine.checkpoint import save_checkpoint


        class S:
            def snap(self, path, state, retired):
                pos = retired
                retired = self._next_seq
                save_checkpoint(path, state, position=pos)
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_eo002_overwritten_binding_is_clean(tmp_path):
    # Flow-sensitive per name: only the LATEST binding before the call
    # reaches it, so the tentative-then-clamp pattern must not flag.
    src = textwrap.dedent('''\
        from gelly_tpu.engine.checkpoint import save_checkpoint


        class S:
            def snap(self, path, state, retired):
                pos = self._next_seq
                pos = retired
                save_checkpoint(path, state, position=pos)
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


EO_CLEAN_SRC = textwrap.dedent('''\
    import os

    from gelly_tpu.engine.checkpoint import (
        read_checkpoint_header,
        save_checkpoint,
    )


    def consume_durably(server, chunks, ckpt_mgr, state, retired):
        for seq, chunk in chunks:
            state = fold(state, chunk)
        ckpt_mgr.save(state, retired)
        server.ack(retired)              # ack AFTER the durability write


    def lossy_pipeline(engine):
        # auto_ack=True with no checkpoint in scope: the documented
        # lossy-tolerant mode, not a finding.
        return IngestServer(port=0, auto_ack=True)


    def snapshot_retired(path, state, chunks_consumed):
        pos = chunks_consumed
        save_checkpoint(path, state, position=pos)


    def export_trace(path, payload):
        with open(path, "w") as f:       # not a durable-store path
            f.write(payload)


    def rotate_rotation(files, keep):
        header = read_checkpoint_header(files[-1])
        if header is None:
            return                       # abort path: newest unreadable
        for old in files[:-keep]:
            os.unlink(old)
''')


def test_eo_clean_fixture_produces_zero_findings(tmp_path):
    findings = _lint_src(tmp_path, EO_CLEAN_SRC)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_eo004_positive_guard_spelling_is_clean(tmp_path):
    # `if header is not None: <prune>` after the validation is the
    # positive spelling of the abort path (fall-through aborts).
    src = textwrap.dedent('''\
        import os

        from gelly_tpu.engine.checkpoint import read_checkpoint_header


        def rotate_rotation(files, keep):
            header = read_checkpoint_header(files[-1])
            if header is not None:
                for old in files[:-keep]:
                    os.unlink(old)
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_eo004_needs_the_abort_path_not_just_the_read(tmp_path):
    # Validation without a possible abort between it and the delete is
    # decoration: the torn newest file would still lose its fallbacks.
    src = "\n".join(
        ln for ln in EO_CLEAN_SRC.splitlines()
        if "if header is None" not in ln and "abort path" not in ln
    ) + "\n"
    findings = _lint_src(tmp_path, src)
    assert [f.rule for f in findings] == ["EO004"]


# --------------------------------------------------------------------- #
# WP rules

WP_SRC = textwrap.dedent('''\
    from gelly_tpu.ingest import wire


    class BadServer:
        def __init__(self, q):
            self._next_seq = 0
            self._q = q
            self._unacked = {}

        def conn_loop(self, recv, sock):
            while True:
                ftype, seq, payload, crc_ok = wire.read_frame_checked(recv)
                self._q.put((seq, payload))              # M-WP001-STAGE
                self._next_seq = seq + 1                 # M-WP001-SEQ
                if not crc_ok:
                    continue

        def torn(self, recv):
            try:
                frame = wire.read_frame(recv)
            except wire.TruncatedFrame:
                self._next_seq += 1                      # M-WP002
            return frame

        def reject_path(self, sock, seq, expect):
            if seq > expect:
                sock.sendall(wire.pack_frame(wire.REJECT, expect))
                self._next_seq = expect                  # M-WP002-REJ

        def on_reject(self):
            self._unacked.clear()                        # M-WP003
''')


def test_wp_rules_flag_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, WP_SRC)
    got = {(f.rule, f.line) for f in findings}
    assert got == {
        ("WP001", _line_of(WP_SRC, "M-WP001-STAGE")),
        ("WP001", _line_of(WP_SRC, "M-WP001-SEQ")),
        ("WP002", _line_of(WP_SRC, "M-WP002")),
        ("WP002", _line_of(WP_SRC, "M-WP002-REJ")),
        ("WP003", _line_of(WP_SRC, "M-WP003")),
    }, "\n".join(f.render() for f in findings)


WP_CLEAN_SRC = textwrap.dedent('''\
    from gelly_tpu.ingest import wire


    class GoodServer:
        """The ingest/server.py shape: CRC guard first, REJECT paths
        read-only, resend trims bounded by ack-derived sequences."""

        def __init__(self, q):
            self._next_seq = 0
            self._q = q
            self._unacked = {}

        def conn_loop(self, recv, sock):
            while True:
                ftype, seq, payload, crc_ok = wire.read_frame_checked(recv)
                if not crc_ok:
                    sock.sendall(wire.pack_frame(wire.REJECT, seq))
                    continue
                self._q.put((seq, payload))
                self._next_seq = seq + 1

        def raising_reader(self, recv):
            # read_frame verifies the CRC before returning: callers are
            # exempt from the WP001 guard requirement.
            ftype, seq, payload = wire.read_frame(recv)
            self._next_seq = seq + 1
            return payload

        def on_ack(self, seq):
            for s in [s for s in self._unacked if s < seq]:
                del self._unacked[s]

        def rewind(self, server_next):
            for s in [s for s in self._unacked if s < server_next]:
                del self._unacked[s]
''')


def test_wp_clean_fixture_produces_zero_findings(tmp_path):
    findings = _lint_src(tmp_path, WP_CLEAN_SRC)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_wp002_nested_def_in_handler_is_clean(tmp_path):
    # A nested def inside a wire-exception handler runs LATER, under
    # its own contract — its body must not be mistaken for a mutation
    # of the handler path (the same-scope pruning rule).
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def torn(self, recv, defer):
                try:
                    frame = wire.read_frame(recv)
                except wire.TruncatedFrame:
                    def _later():
                        self._next_seq += 1
                    defer(_later)
                return frame
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_wp001_positive_crc_branch_is_clean(tmp_path):
    # `if crc_ok: <stage + advance>` dominates the mutations just as
    # well as the abort-style inverse — the positive spelling must not
    # flag (the serving-plane refactors are gated on WP001-clean).
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def conn_loop(self, recv, q):
                while True:
                    ftype, seq, payload, crc_ok = \\
                        wire.read_frame_checked(recv)
                    if crc_ok:
                        q.put((seq, payload))
                        self._next_seq = seq + 1
    ''')
    findings = _lint_src(tmp_path, src)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_wp001_mutation_inside_the_reject_branch_flags(tmp_path):
    # The canonical violation: advancing/staging on the CRC-failure
    # path itself. The abort guard must never bless the statements it
    # exists to abort around.
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def conn_loop(self, recv, q):
                while True:
                    ftype, seq, payload, crc_ok = \\
                        wire.read_frame_checked(recv)
                    if not crc_ok:
                        self._next_seq = seq + 1         # M-IN-ABORT
                        continue
                    q.put((seq, payload))
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("WP001", _line_of(src, "M-IN-ABORT"))]


def test_wp001_positive_guard_polarity(tmp_path):
    # Both positive-guard spellings of the reject-path mutation must
    # flag: the else-branch of `if crc_ok:`, and the fall-through after
    # an `if crc_ok: return` accept path — a positive guard's line
    # never blesses later statements.
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def with_else(self, recv, q):
                ftype, seq, payload, crc_ok = \\
                    wire.read_frame_checked(recv)
                if crc_ok:
                    q.put((seq, payload))
                    return payload
                else:
                    self._next_seq = seq + 1             # M-ELSE-ADV

            def fall_through(self, recv, q):
                ftype, seq, payload, crc_ok = \\
                    wire.read_frame_checked(recv)
                if crc_ok:
                    return payload
                self._next_seq = seq + 1                 # M-FALL-ADV
    ''')
    findings = _lint_src(tmp_path, src)
    assert {(f.rule, f.line) for f in findings} == {
        ("WP001", _line_of(src, "M-ELSE-ADV")),
        ("WP001", _line_of(src, "M-FALL-ADV")),
    }, "\n".join(f.render() for f in findings)


def test_wp001_polarity_keys_on_the_crc_name_itself(tmp_path):
    # A `not` over some OTHER operand must not flip the guard negative
    # (the duplicate-drop idiom), and a comparison-spelled negation
    # (`crc_ok == False`) must not read as a positive guard.
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def dedup(self, recv, q, seen):
                ftype, seq, payload, crc_ok = \\
                    wire.read_frame_checked(recv)
                if crc_ok and not (seq in seen):
                    q.put((seq, payload))                # verified path
                    self._next_seq = seq + 1

            def compare_spelled(self, recv, q):
                ftype, seq, payload, crc_ok = \\
                    wire.read_frame_checked(recv)
                if crc_ok == False:                      # noqa: E712
                    self._next_seq = seq + 1             # M-CMP-ADV
                    return
                q.put((seq, payload))
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("WP001", _line_of(src, "M-CMP-ADV"))], \
        "\n".join(f.render() for f in findings)


def test_wp001_success_branch_abort_does_not_bless_fall_through(tmp_path):
    # A `return` on the SUCCESS path (the else of `if not crc_ok:`)
    # proves nothing about the fall-through, which runs only on CRC
    # failure — the canonical violation must still flag; the else
    # branch itself is the verified path and stays clean.
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def conn(self, recv, q, log):
                ftype, seq, payload, crc_ok = \\
                    wire.read_frame_checked(recv)
                if not crc_ok:
                    log()
                else:
                    q.put((seq, payload))                # verified path
                    return payload
                self._next_seq = seq + 1                 # M-FALL-BAD
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("WP001", _line_of(src, "M-FALL-BAD"))], \
        "\n".join(f.render() for f in findings)


def test_wp003_flags_unbounded_del(tmp_path):
    src = WP_CLEAN_SRC.replace(
        "for s in [s for s in self._unacked if s < seq]:",
        "for s in list(self._unacked):",
    )
    findings = _lint_src(tmp_path, src)
    assert [f.rule for f in findings] == ["WP003"]


def test_wp003_in_flight_bound_is_not_ack_derived(tmp_path):
    # A trim bounded by the sender's OWN in-flight counter is clear()
    # spelled as a filter (next_seq is above every buffered frame): the
    # `seq` suffix alone must not bless it.
    src = WP_CLEAN_SRC.replace(
        "for s in [s for s in self._unacked if s < seq]:",
        "for s in [s for s in self._unacked if s < self._next_seq]:",
    )
    findings = _lint_src(tmp_path, src)
    assert [f.rule for f in findings] == ["WP003"]


def test_wp002_flags_reject_in_else_branch(tmp_path):
    src = textwrap.dedent('''\
        from gelly_tpu.ingest import wire


        class S:
            def handle(self, sock, crc_ok, expect):
                if crc_ok:
                    pass
                else:
                    sock.sendall(wire.pack_frame(wire.REJECT, expect))
                    self._next_seq = expect              # M-ELSE
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("WP002", _line_of(src, "M-ELSE"))]


def test_eo003_keyword_mode_and_pathlib_spellings(tmp_path):
    src = textwrap.dedent('''\
        from pathlib import Path


        def tear(store_dir, ckpt_path, obj):
            with open(store_dir + "/MANIFEST.json", mode="w") as f:  # M-KW
                f.write(str(obj))
            with Path(ckpt_path).open("w") as f:                     # M-PL
                f.write(str(obj))


        def read_side(store_dir):
            with open(store_dir + "/MANIFEST.json") as f:   # read: clean
                return f.read()
    ''')
    findings = _lint_src(tmp_path, src)
    assert {(f.rule, f.line) for f in findings} == {
        ("EO003", _line_of(src, "M-KW")),
        ("EO003", _line_of(src, "M-PL")),
    }, "\n".join(f.render() for f in findings)


def test_ob002_inactive_on_a_partial_package_subset():
    # Linting only gelly_tpu/obs/ pulls in the glossary but not the
    # package's emit sites: OB002 must recognize the under-collected
    # subset and stay silent instead of mass-flagging live entries.
    findings = contracts.lint_paths(
        REPO, [os.path.join(REPO, "gelly_tpu", "obs")])
    assert [f for f in findings if f.rule == "OB002"] == [], \
        "\n".join(f.render() for f in findings)


def test_ob002_uncovered_module_does_not_mask_covered_ones(tmp_path):
    # Coverage is per glossary MODULE: one bus.py from an un-covered
    # package (its sibling sources not in the lint set) must not skip
    # dead-entry checks for a fully-covered one that sorts after it.
    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir()
    b.mkdir()
    (a / "bus.py").write_text(
        '"""G.\n\n``zapp.alive``  emitted\n``zapp.dead``   dead\n"""\n')
    (a / "mod.py").write_text(
        'def p(bus):\n    bus.inc("zapp.alive")\n')
    (b / "bus.py").write_text('"""G.\n\n``aaa.other``  elsewhere\n"""\n')
    (b / "helper.py").write_text("x = 1\n")  # NOT linted: b uncovered
    findings = contracts.lint_paths(str(tmp_path), [
        str(a / "bus.py"), str(a / "mod.py"), str(b / "bus.py")])
    got = {(f.rule, os.path.basename(os.path.dirname(f.path)))
           for f in findings}
    assert got == {("OB002", "a")}, \
        "\n".join(f.render() for f in findings)
    assert "zapp.dead" in findings[0].message


def test_eo003_hoisted_path_binding_still_flags(tmp_path):
    # Hoisting the path into a local must not launder the marker: the
    # scan chases the same assignment chains EO002 does.
    src = textwrap.dedent('''\
        def tear(store_dir, obj):
            target = store_dir + "/MANIFEST.json"
            with open(target, "w") as f:                 # M-HOIST
                f.write(str(obj))
    ''')
    findings = _lint_src(tmp_path, src)
    assert [(f.rule, f.line) for f in findings] \
        == [("EO003", _line_of(src, "M-HOIST"))]


def test_ob_collection_ignores_non_bus_receivers(tmp_path):
    # busy_tracker.gauge(...) never touches the bus: substring matching
    # on "bus" would flag it OB001 and poison OB002/OB003 coverage.
    bus = '"""Glossary.\n\n``app.frames``      frames seen\n"""\n'
    mod = textwrap.dedent('''\
        def publish(bus, busy_tracker, t):
            bus.inc("app.frames")
            busy_tracker.gauge("app.latency", t)
    ''')
    findings = _lint_files(tmp_path, {"bus.py": bus, "mod.py": mod})
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# OB rules (glossary in a fixture bus.py; checker keys on the basename)

OB_BUS_SRC = '''\
"""Mini event bus with a glossary table.

``app.frames``                        frames seen
``app.depth``                         staging depth (gauge)
``app.mixed``                         used as counter AND gauge
``app.dead``                          never emitted anywhere
"""
'''

OB_MOD_SRC = textwrap.dedent('''\
    def publish(bus, depth, prefix):
        bus.inc("app.frames")
        bus.gauge("app.depth", depth)
        bus.inc("app.rogue")                             # M-OB001
        bus.inc("app.mixed")
        bus.gauge("app.mixed", depth)                    # M-OB003
''')


def test_ob_rules_flag_line_anchored(tmp_path):
    findings = _lint_files(tmp_path, {"bus.py": OB_BUS_SRC,
                                      "mod.py": OB_MOD_SRC})
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    assert got == {
        ("OB001", "mod.py", _line_of(OB_MOD_SRC, "M-OB001")),
        ("OB002", "bus.py", _line_of(OB_BUS_SRC, "app.dead")),
        ("OB003", "mod.py", _line_of(OB_MOD_SRC, "M-OB003")),
    }, "\n".join(f.render() for f in findings)


def test_ob_glossary_round_trip_is_clean(tmp_path):
    # Every emitted name documented, every documented name emitted —
    # including a prefix-parameterized f-string name, which must count
    # as emitting its ``*.suffix`` family (the publish_checkpoint
    # idiom) rather than flag OB001/OB002.
    bus = ('"""Glossary.\n'
           '\n'
           '``app.frames``      frames seen\n'
           '``res.checkpoints``  prefix-published checkpoint writes\n'
           '"""\n')
    mod = textwrap.dedent('''\
        def publish(bus, prefix):
            bus.inc("app.frames")
            bus.inc(f"{prefix}.checkpoints")
    ''')
    findings = _lint_files(tmp_path, {"bus.py": bus, "mod.py": mod})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_ob001_flags_undocumented_wildcard_family(tmp_path):
    # A prefixed f-string name whose suffix NO glossary entry carries is
    # the publish_checkpoint-idiom drift class: it must flag, not slip
    # through the wildcard path undocumented.
    bus = '"""Glossary.\n\n``app.frames``      frames seen\n"""\n'
    mod = textwrap.dedent('''\
        def publish(bus, prefix):
            bus.inc("app.frames")
            bus.inc(f"{prefix}.rogue_family")            # M-OB001-WILD
    ''')
    findings = _lint_files(tmp_path, {"bus.py": bus, "mod.py": mod})
    assert [(f.rule, f.line) for f in findings] \
        == [("OB001", _line_of(mod, "M-OB001-WILD"))]
    assert ".rogue_family" in findings[0].message


def test_ob_glossary_rules_inactive_without_a_glossary_module(tmp_path):
    # Without the glossary module in the lint set (rule-fixture runs,
    # partial-path invocations) OB001/OB002 must stay silent instead of
    # flagging every name as undocumented. OB003 is glossary-FREE by
    # design (the counter/gauge collision is a property of the call
    # sites alone), so the mixed name still flags.
    findings = _lint_src(tmp_path, OB_MOD_SRC, name="mod.py")
    assert [f.rule for f in findings] == ["OB003"]


# --------------------------------------------------------------------- #
# suppression scoping

def test_suppression_silences_one_rule(tmp_path):
    src = EO_SRC.replace(
        "server.ack(seq + 1)                          # M-EO001",
        "server.ack(seq + 1)  # graphlint: disable=EO001",
    )
    findings = _lint_src(tmp_path, src)
    rules = {f.rule for f in findings}
    assert "EO001" in rules  # the auto_ack site still flags
    assert ("EO001", _line_of(src, "disable=EO001")) \
        not in {(f.rule, f.line) for f in findings}
    assert {"EO002", "EO003", "EO004"} <= rules  # others survive


def test_suppression_all_and_wrong_rule(tmp_path):
    src = WP_SRC.replace(
        "self._unacked.clear()                        # M-WP003",
        "self._unacked.clear()  # graphlint: disable=all",
    )
    assert not any(f.rule == "WP003"
                   for f in _lint_src(tmp_path, src))
    src2 = WP_SRC.replace(
        "self._unacked.clear()                        # M-WP003",
        "self._unacked.clear()  # graphlint: disable=EO001",
    )
    assert any(f.rule == "WP003" for f in _lint_src(tmp_path, src2))


# --------------------------------------------------------------------- #
# AL rules (best-effort alert plane must stay outside the exactly-once
# protocol state: ISSUE 20)

AL_SRC = textwrap.dedent('''\
    from gelly_tpu.ingest import wire


    class BadAlertPusher:
        """Alert delivery that leaks into exactly-once state: every
        mutation inside the ALERT-packing scope must flag."""

        def __init__(self, sock, q):
            self._sock = sock
            self._q = q
            self._next_seq = 0
            self._unacked = {}

        def push_alert(self, seq, body):
            frame = wire.pack_frame(wire.ALERT, seq, body)
            self._sock.sendall(frame)
            self._next_seq = seq + 1                     # M-AL001-SEQ
            self._unacked[seq] = frame                   # M-AL001-BUF
            self._q.put((seq, body))                     # M-AL001-STAGE
''')


def test_al001_flags_line_anchored(tmp_path):
    findings = _lint_src(tmp_path, AL_SRC)
    got = {(f.rule, f.line) for f in findings}
    assert got == {
        ("AL001", _line_of(AL_SRC, "M-AL001-SEQ")),
        ("AL001", _line_of(AL_SRC, "M-AL001-BUF")),
        ("AL001", _line_of(AL_SRC, "M-AL001-STAGE")),
    }, "\n".join(f.render() for f in findings)


AL_CLEAN_SRC = textwrap.dedent('''\
    from gelly_tpu.ingest import wire


    class GoodAlertPusher:
        """The ingest/server.py shape: the push closure only packs the
        ALERT frame and bumps best-effort delivery counters — the data
        plane's seq/ack/resend state is never touched."""

        def __init__(self, sock, bus):
            self._sock = sock
            self._bus = bus

        def push_alert(self, alert_seq, body):
            frame = wire.pack_frame(wire.ALERT, alert_seq, body)
            try:
                self._sock.sendall(frame)
                self._bus.inc("alerts.pushed")
            except OSError:
                self._bus.inc("alerts.dropped")
''')


def test_al001_clean_push_closure(tmp_path):
    findings = _lint_src(tmp_path, AL_CLEAN_SRC)
    assert [f for f in findings if f.rule == "AL001"] == [], \
        "\n".join(f.render() for f in findings)


def test_al001_inactive_without_alert_send(tmp_path):
    # The same mutations in a DATA-sending scope are WP territory, not
    # AL001's: the rule keys on the ALERT frame type reaching
    # pack_frame in the scope.
    src = AL_SRC.replace("wire.ALERT", "wire.DATA")
    findings = _lint_src(tmp_path, src)
    assert not any(f.rule == "AL001" for f in findings), \
        "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- #
# every seeded violation flips the CLI exit code (ISSUE 11 acceptance)

_RULE_SEEDS = {
    "EO001": {"mod.py": EO_SRC},
    "EO002": {"mod.py": EO_SRC},
    "EO003": {"mod.py": EO_SRC},
    "EO004": {"mod.py": EO_SRC},
    "WP001": {"mod.py": WP_SRC},
    "WP002": {"mod.py": WP_SRC},
    "WP003": {"mod.py": WP_SRC},
    "AL001": {"mod.py": AL_SRC},
    "OB001": {"bus.py": OB_BUS_SRC, "mod.py": OB_MOD_SRC},
    "OB002": {"bus.py": OB_BUS_SRC, "mod.py": OB_MOD_SRC},
    "OB003": {"bus.py": OB_BUS_SRC, "mod.py": OB_MOD_SRC},
}


@pytest.mark.parametrize("rule", sorted(_RULE_SEEDS))
def test_seeded_violation_turns_exit_nonzero(tmp_path, rule, capsys):
    for name, src in _RULE_SEEDS[rule].items():
        (tmp_path / name).write_text(src)
    rc = analysis_main(["contracts", str(tmp_path), "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 1
    assert rule in out


# --------------------------------------------------------------------- #
# CLI exit-code contract

def test_cli_contracts_subcommand_exit_zero_on_tip(capsys):
    rc = analysis_main(["contracts", os.path.join(REPO, "gelly_tpu"),
                        "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    assert "contracts: 0 finding(s)" in out
    assert "analysis clean (contracts)" in out


def test_cli_json_format_covers_contracts(tmp_path, capsys):
    (tmp_path / "mod.py").write_text(EO_SRC)
    rc = analysis_main(["contracts", str(tmp_path), "--root", REPO,
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["ok"] is False
    assert payload["total"] == payload["tools"]["contracts"]["count"] == 5
    f0 = payload["tools"]["contracts"]["findings"][0]
    assert {"path", "line", "rule", "message", "hint"} <= set(f0)


@pytest.mark.slow  # tier-1 budget: contracts lane; subcommand smoke stays
def test_cli_all_includes_contracts(capsys):
    rc = analysis_main(["--all", "--root", REPO, "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True
    assert "contracts" in payload["tools"]


@pytest.mark.slow  # tier-1 budget: contracts lane; subcommand smoke stays
def test_cli_skip_contracts(capsys):
    rc = analysis_main(["--all", "--root", REPO, "--skip-contracts",
                        "--format=json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(payload["tools"]) == {"abi", "jitlint", "racecheck",
                                     "plancheck", "liveness"}


def test_cli_list_rules_includes_contract_rules(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("EO001", "EO004", "WP001", "WP003", "AL001", "OB001",
                "OB003"):
        assert rid in out


# --------------------------------------------------------------------- #
# OB rules: histogram kind (bus.observe) — ISSUE 14 satellite


OB_HIST_BUS_SRC = '''\
"""Mini event bus with a histogram glossary section.

``app.frames``                        frames seen

Histogram names:

``app.fold_ms``                       fold dispatch wall
``app.dead_hist_ms``                  documented but never observed
"""
'''


def test_ob001_flags_undocumented_histogram(tmp_path):
    mod = textwrap.dedent('''\
        def publish(bus, dt):
            bus.inc("app.frames")
            bus.observe("app.fold_ms", dt)
            bus.observe("app.rogue_ms", dt)              # H-OB001
    ''')
    findings = _lint_files(tmp_path, {"bus.py": OB_HIST_BUS_SRC,
                                      "mod.py": mod})
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    assert ("OB001", "mod.py", _line_of(mod, "H-OB001")) in got
    (f001,) = [f for f in findings if f.rule == "OB001"]
    assert "histogram" in f001.message


def test_ob002_flags_dead_histogram_entry(tmp_path):
    mod = textwrap.dedent('''\
        def publish(bus, dt):
            bus.inc("app.frames")
            bus.observe("app.fold_ms", dt)
    ''')
    findings = _lint_files(tmp_path, {"bus.py": OB_HIST_BUS_SRC,
                                      "mod.py": mod})
    assert [(f.rule, os.path.basename(f.path), f.line)
            for f in findings] \
        == [("OB002", "bus.py",
             _line_of(OB_HIST_BUS_SRC, "app.dead_hist_ms"))]


def test_ob002_wildcard_observe_site_covers_histogram_family(tmp_path):
    # The watermark-ledger idiom: one f-string observe site publishes
    # the whole <prefix>.e2e_ingress_to_fold_ms family — it must count
    # as emitting the documented representative, both ways.
    bus = ('"""Glossary.\n'
           '\n'
           '``app.frames``      frames seen\n'
           '``eng.e2e_ms``      e2e latency family representative\n'
           '"""\n')
    mod = textwrap.dedent('''\
        def publish(bus, prefix, dt):
            bus.inc("app.frames")
            bus.observe(f"{prefix}.e2e_ms", dt)
    ''')
    findings = _lint_files(tmp_path, {"bus.py": bus, "mod.py": mod})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_ob003_counter_histogram_collision_both_ways(tmp_path):
    # Collision across the NEW kind: one name inc'd and observe'd. The
    # finding anchors at the higher-precedence site (the histogram),
    # never both — and a name used consistently as a histogram in two
    # places stays clean.
    bus = ('"""Glossary.\n'
           '\n'
           '``app.mixed_ms``    oops, counter and histogram\n'
           '``app.clean_ms``    histogram in two modules\n'
           '"""\n')
    mod = textwrap.dedent('''\
        def publish(bus, dt):
            bus.inc("app.mixed_ms")
            bus.observe("app.mixed_ms", dt)              # H-OB003
            bus.observe("app.clean_ms", dt)
    ''')
    mod2 = textwrap.dedent('''\
        def publish2(bus, dt):
            bus.observe("app.clean_ms", dt)
    ''')
    findings = _lint_files(tmp_path, {"bus.py": bus, "mod.py": mod,
                                      "mod2.py": mod2})
    assert [(f.rule, f.line) for f in findings] \
        == [("OB003", _line_of(mod, "H-OB003"))]
    assert "histogram" in findings[0].message


def test_ob003_gauge_histogram_collision_flags_histogram_site(tmp_path):
    mod = textwrap.dedent('''\
        def publish(bus, dt):
            bus.gauge("app.depth_ms", dt)
            bus.observe("app.depth_ms", dt)              # GH-OB003
    ''')
    findings = _lint_src(tmp_path, mod, name="mod.py")
    assert [(f.rule, f.line) for f in findings] \
        == [("OB003", _line_of(mod, "GH-OB003"))]


def test_tip_histogram_glossary_covers_issue14_metrics():
    # The ISSUE 14 histogram set must be documented AND emitted on tip:
    # deleting a call site without the glossary entry (or the reverse)
    # regresses here the same way the PR 11 audit names do.
    import gelly_tpu

    root = os.path.dirname(gelly_tpu.__file__)
    c = contracts.ContractChecker(root)
    findings = c.lint_paths([root])
    assert [f for f in findings if f.rule.startswith("OB")] == []
    for name in ("engine.fold_dispatch_ms", "engine.merge_emit_ms",
                 "resilience.checkpoint_write_ms",
                 "ingest.receive_to_stage_ms", "tenants.round_ms",
                 "multiquery.emit_ms", "engine.e2e_ingress_to_fold_ms",
                 "engine.e2e_ingress_to_durable_ms"):
        assert name in c._glossary, name
    hist_sites = {s.name for s in c._emits if s.kind == "histogram"}
    assert {"engine.fold_dispatch_ms", "engine.merge_emit_ms",
            "ingest.receive_to_stage_ms", "tenants.round_ms",
            "multiquery.emit_ms"} <= hist_sites
    # the watermark ledger's wildcard families
    assert {".e2e_ingress_to_fold_ms", ".e2e_ingress_to_durable_ms",
            ".checkpoint_write_ms"} <= {
        s.name for s in c._emits if s.kind == "histogram" and s.wildcard}
