"""Slice / SnapshotStream parity tests.

Mirrors the reference's 9 slice×{fold,reduce,apply}×{OUT,IN,ALL} mini-cluster
tests (T/test/operations/TestSlice.java:41-199) on the canonical 5-vertex /
7-edge fixture, with the same golden outputs, plus multi-window and
neighborhood coverage the reference leaves untested (buildNeighborhood has a
'TODO: write tests' marker, M/SimpleEdgeStream.java:520).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.ops import segments

# TestSlice goldens (sum of edge values per group vertex, one 1s window).
EXPECTED = {
    "out": {1: 25, 2: 23, 3: 69, 4: 45, 5: 51},
    "in": {1: 51, 2: 12, 3: 36, 4: 34, 5: 80},
    "all": {1: 76, 2: 35, 3: 105, 4: 79, 5: 131},
}


def fixture_stream(reference_edges, chunk_size=3):
    return edge_stream_from_edges(
        reference_edges, vertex_capacity=16, chunk_size=chunk_size
    )


def drain_updates(it, ctx):
    out = {}
    for upd in it:
        for k, v in upd.to_pairs(ctx):
            out[k] = int(v) if np.ndim(v) == 0 else v
    return out


@pytest.mark.parametrize("direction", ["out", "in", "all"])
def test_reduce_on_edges(reference_edges, direction):
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, direction)
    got = drain_updates(snap.reduce_on_edges(lambda a, b: a + b), s.ctx)
    assert got == EXPECTED[direction]


@pytest.mark.parametrize("direction", ["out", "in", "all"])
def test_fold_neighbors(reference_edges, direction):
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, direction)
    # SumEdgeValues fold (TestSlice.java:203-210): acc + edge value.
    got = drain_updates(
        snap.fold_neighbors(
            jnp.zeros((), jnp.float32), lambda acc, v, nbr, val: acc + val
        ),
        s.ctx,
    )
    assert got == EXPECTED[direction]


@pytest.mark.parametrize("direction", ["out", "in", "all"])
def test_apply_on_neighbors_vectorized(reference_edges, direction):
    # SumEdgeValuesApply golden (TestSlice.java:222-240): 'big' iff sum > 50.
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, direction)

    def apply_fn(view):
        sums = segments.masked_scatter_add(
            jnp.zeros((16,), jnp.float32), view.key, view.val, view.valid
        )
        seen = jnp.zeros((16,), bool).at[
            jnp.where(view.valid, view.key, 0)
        ].max(view.valid, mode="drop")
        return sums, seen

    results = list(snap.apply_on_neighbors(apply_fn))
    assert len(results) == 1
    _, (sums, seen) = results[0]
    got = {
        int(s.ctx.decode(np.array([i]))[0]): ("big" if float(sums[i]) > 50 else "small")
        for i in np.nonzero(np.asarray(seen))[0]
    }
    expected = {
        k: ("big" if v > 50 else "small") for k, v in EXPECTED[direction].items()
    }
    assert got == expected


def test_apply_per_vertex_host_adapter(reference_edges):
    # Reference-style sequential UDF over the neighbor Iterable.
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, "out")
    got = {}
    for _, view in snap.views():
        for vid, nbrs in view.per_vertex(s.ctx):
            got[vid] = sum(v for _, v in nbrs)
    assert got == EXPECTED["out"]


def test_neighbor_ids_visible_to_fold(reference_edges):
    # fold sees (vertex, neighbor) slots, not just values: count neighbors.
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, "all")
    got = drain_updates(
        snap.fold_neighbors(
            jnp.zeros((), jnp.int32),
            lambda acc, v, nbr, val: acc + 1,
        ),
        s.ctx,
    )
    assert got == {1: 3, 2: 2, 3: 4, 4: 2, 5: 3}  # degrees


def test_multiple_windows_event_time():
    # Two tumbling 100ms windows: edges 0-2 in w0, 3-4 in w1.
    edges = [(1, 2, 10.0), (1, 3, 20.0), (2, 3, 5.0), (1, 2, 7.0), (3, 1, 2.0)]
    ts = np.array([0, 10, 50, 120, 150])
    s = edge_stream_from_edges(
        edges, vertex_capacity=8, chunk_size=2,
        time=__import__("gelly_tpu").TimeCharacteristic.EVENT, timestamps=ts,
    )
    snap = s.slice(100, "out")
    per_window = {}
    for upd in snap.reduce_on_edges(lambda a, b: a + b):
        per_window[upd.window] = dict(upd.to_pairs(s.ctx))
    assert {int(k): int(v) for k, v in per_window[0].items()} == {1: 30, 2: 5}
    assert {int(k): int(v) for k, v in per_window[1].items()} == {1: 7, 3: 2}
    assert snap.stats["windows_closed"] == 2


def test_window_buffer_overflow_raises(reference_edges):
    s = fixture_stream(reference_edges, chunk_size=2)
    snap = s.slice(1000, "out", window_capacity=4)
    with pytest.raises(ValueError, match="window buffer overflow"):
        list(snap.reduce_on_edges(lambda a, b: a + b))


def test_build_neighborhood(reference_edges):
    s = fixture_stream(reference_edges)
    nstream = s.build_neighborhood(directed=False)
    assert nstream.neighbors_of(3) == [1, 2, 4, 5]
    assert nstream.neighbors_of(1) == [2, 3, 5]
    assert nstream.neighbors_of(42) == []


def test_build_neighborhood_directed(reference_edges):
    s = fixture_stream(reference_edges)
    nstream = s.build_neighborhood(directed=True)
    assert nstream.neighbors_of(3) == [4, 5]
    assert nstream.neighbors_of(5) == [1]


def test_fold_neighbors_tuple_accumulator(reference_edges):
    # The reference's SumEdgeValues folds into a Tuple2 (id, sum)
    # (TestSlice.java:203-210): pytree accumulators must work.
    s = fixture_stream(reference_edges)
    snap = s.slice(1000, "out")
    init = (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.float32))
    got = {}
    for upd in snap.fold_neighbors(
        init, lambda acc, v, nbr, val: (v, acc[1] + val)
    ):
        for k, (vid, total) in upd.to_pairs(s.ctx):
            got[k] = (int(vid), int(total))
    slot_of = {int(r): i for i, r in enumerate(s.ctx.table._rev.tolist())}
    assert got == {k: (slot_of[k], v) for k, v in EXPECTED["out"].items()}


def test_sparse_neighborhood_matches_dense():
    from gelly_tpu.core.neighborhood import NeighborhoodStream

    rng = np.random.default_rng(6)
    edges = list(zip(rng.integers(0, 32, 150).tolist(),
                     rng.integers(0, 32, 150).tolist()))

    def stream():
        return edge_stream_from_edges(edges, vertex_capacity=32, chunk_size=16)

    dense = NeighborhoodStream(stream())
    sparse = NeighborhoodStream(stream(), max_degree=32)
    for v in {a for a, _ in edges} | {b for _, b in edges}:
        assert dense.neighbors_of(v) == sparse.neighbors_of(v), v


def test_sparse_neighborhood_million_vertices_and_overflow():
    import pytest

    from gelly_tpu.core.neighborhood import NeighborhoodStream

    n_v = 1 << 20
    rng = np.random.default_rng(7)
    ids = rng.choice(n_v, 40, replace=False).astype(np.int64)
    edges = [(int(ids[i]), int(ids[i + 1])) for i in range(39)]
    s = edge_stream_from_edges(edges, vertex_capacity=n_v, chunk_size=16)
    ns = NeighborhoodStream(s, max_degree=4)
    assert ns.neighbors_of(int(ids[1])) == sorted({int(ids[0]), int(ids[2])})

    # Hot vertex past the cap raises (no silently truncated neighborhoods).
    star = [(0, i) for i in range(1, 20)]
    s2 = edge_stream_from_edges(star, vertex_capacity=64, chunk_size=8)
    with pytest.raises(ValueError, match="max_degree"):
        NeighborhoodStream(s2, max_degree=4).final_adjacency()
