"""Resilient driver: checkpointed folds, retry/backoff, watchdog, fault
injection, degradation, and kill -9 crash recovery (``pytest -m faults``)."""

import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from gelly_tpu.engine import faults
from gelly_tpu.engine.checkpoint import load_checkpoint
from gelly_tpu.engine.resilience import (
    CheckpointManager,
    ResilienceConfig,
    ResilientRunner,
    RetriesExhausted,
    RetryPolicy,
    Watchdog,
    WatchdogTimeout,
)
from gelly_tpu.utils import native

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------- #
# a tiny order-sensitive fold: state' = state * 3 + chunk. Any skipped,
# duplicated, or reordered chunk changes the final value, so equality with
# an uninterrupted run is an exactly-once proof.


def _step(s, c):
    return np.int64(s * 3 + c), int(c)


def _clean_run(n):
    s = np.int64(0)
    for c in range(n):
        s, _ = _step(s, c)
    return s


def _fast(**kw):
    kw.setdefault("retry", RetryPolicy(max_attempts=4, base_delay=0.01,
                                       max_delay=0.05))
    kw.setdefault("watchdog_timeout", None)
    kw.setdefault("prefetch_depth", 2)
    return ResilienceConfig(**kw)


# ---------------------------------------------------------------------- #
# units


def test_retry_policy_backoff_and_determinism():
    p = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.5, jitter=0.5)
    d0 = [p.delay(i, random.Random(7)) for i in range(5)]
    d1 = [p.delay(i, random.Random(7)) for i in range(5)]
    assert d0 == d1  # seeded jitter is reproducible
    bases = [0.1, 0.2, 0.4, 0.5, 0.5]
    for d, b in zip(d0, bases):
        assert b <= d <= b * 1.5  # exponential growth, capped, jitter-bounded


def test_watchdog_passes_results_and_errors_and_times_out():
    w = Watchdog(timeout=5.0)
    assert w.call(lambda: 42, "t") == 42
    with pytest.raises(KeyError):
        w.call(lambda: {}["x"], "t")
    w = Watchdog(timeout=0.1)
    t0 = time.monotonic()
    with pytest.raises(WatchdogTimeout):
        w.call(lambda: time.sleep(3.0), "t")
    assert time.monotonic() - t0 < 1.0


def test_checkpoint_manager_rotation_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2, async_write=False)
    for pos in (2, 4, 6, 8):
        mgr.save(np.int64(pos * 10), pos)
    files = mgr.list()
    assert [os.path.basename(f) for f in files] == [
        "ckpt-000000000006.npz", "ckpt-000000000008.npz"
    ]
    state, pos, _, path = mgr.load_latest(like=np.int64(0))
    assert pos == 8 and int(state) == 80 and path == files[-1]


def test_checkpoint_manager_skips_torn_newest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=3, async_write=False)
    mgr.save(np.int64(1), 1)
    mgr.save(np.int64(2), 2)
    newest = mgr.list()[-1]
    with open(newest, "r+b") as f:  # tear the newest file
        f.truncate(os.path.getsize(newest) // 2)
    state, pos, _, path = mgr.load_latest(like=np.int64(0))
    assert pos == 1 and int(state) == 1 and path != newest


def test_stale_tmp_reap_is_prefix_scoped(tmp_path):
    """Manager construction reaps only ITS rotation's crashed-writer
    leftovers: another prefix sharing the directory (one rotation per
    tenant in the multi-tenant engine) may have a write in flight, and
    a directory-wide reap would delete its tmp mid-write."""
    mine = tmp_path / "a-000000000005-x1y2.npz.tmp"
    theirs = tmp_path / "b-000000000009-q3r4.npz.tmp"
    mine.write_bytes(b"torn leftover")
    theirs.write_bytes(b"write in flight")
    CheckpointManager(str(tmp_path), prefix="a", async_write=False)
    assert not mine.exists()  # own leftover reaped at takeover
    assert theirs.exists()  # the other rotation's tmp untouched
    CheckpointManager(str(tmp_path), prefix="b", async_write=False)
    assert not theirs.exists()


def test_checkpoint_tmp_name_matches_reap_scope(tmp_path, monkeypatch):
    """The atomic-rename tmp carries the target basename, so a crashed
    writer's leftover globs under its OWN rotation's prefix-scoped
    reap (an anonymous mkstemp name would never be cleaned up)."""
    import fnmatch

    from gelly_tpu.engine import checkpoint as ckpt_mod

    seen = []
    real_mkstemp = ckpt_mod.tempfile.mkstemp

    def spy(**kw):
        fd, p = real_mkstemp(**kw)
        seen.append(p)
        return fd, p

    monkeypatch.setattr(ckpt_mod.tempfile, "mkstemp", spy)
    mgr = CheckpointManager(str(tmp_path), prefix="t9", async_write=False)
    mgr.save(np.int64(3), 4)
    assert seen and fnmatch.fnmatch(
        os.path.basename(seen[0]), "t9-*.npz.tmp"
    )


def test_checkpoint_manager_async_write_error_surfaces(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path), keep=2,
        retry=RetryPolicy(max_attempts=2, base_delay=0.01),
    )
    plan = faults.FaultPlan([
        faults.Fault("checkpoint_write", at=0, count=10,
                     exc=lambda: PermissionError("disk said no")),
    ])
    with faults.install(plan):
        mgr.save(np.int64(5), 5)
        with pytest.raises(RetriesExhausted) as ei:
            mgr.close()
    assert ei.value.boundary == "checkpoint_write"


# ---------------------------------------------------------------------- #
# driver: retry / watchdog / degradation at each boundary


def test_transient_step_fault_is_retried_to_success():
    plan = faults.FaultPlan([faults.Fault("step", at=3, count=2)])
    with faults.install(plan):
        r = ResilientRunner(_step, list(range(10)), np.int64(0),
                            config=_fast())
        final = r.run()
    assert int(final) == int(_clean_run(10))
    assert r.stats["retries"] == 2
    assert plan.fired == [("step", 3, "raise"), ("step", 4, "raise")]


def test_permanent_fault_is_not_retried():
    plan = faults.FaultPlan([
        faults.Fault("step", at=2, retryable=False),
    ])
    with faults.install(plan):
        r = ResilientRunner(_step, list(range(10)), np.int64(0),
                            config=_fast())
        with pytest.raises(faults.FaultInjected):
            r.run()
    assert r.stats["retries"] == 0


def test_retries_exhausted_is_actionable():
    plan = faults.FaultPlan([faults.Fault("step", at=1, count=50)])
    with faults.install(plan):
        r = ResilientRunner(_step, list(range(10)), np.int64(0),
                            config=_fast())
        with pytest.raises(RetriesExhausted) as ei:
            r.run()
    assert ei.value.boundary == "step"
    assert "attempts" in str(ei.value)


def test_hang_hits_watchdog_and_is_retried():
    plan = faults.FaultPlan([
        faults.Fault("step", at=2, kind="hang", hang_seconds=10.0),
    ])
    t0 = time.monotonic()
    with faults.install(plan):
        r = ResilientRunner(_step, list(range(6)), np.int64(0),
                            config=_fast(watchdog_timeout=0.2))
        final = r.run()
    assert time.monotonic() - t0 < 5.0  # did not sit out the 10s hang
    assert int(final) == int(_clean_run(6))
    assert r.stats["retries"] == 1


def test_h2d_boundary_fault_is_retried():
    staged = []
    plan = faults.FaultPlan([faults.Fault("h2d", at=1, count=1)])
    with faults.install(plan):
        r = ResilientRunner(
            _step, list(range(5)), np.int64(0), config=_fast(),
            stage=lambda c: (staged.append(c), c)[1],
        )
        final = r.run()
    assert int(final) == int(_clean_run(5))
    assert r.stats["retries"] == 1
    assert staged == list(range(5))  # retried chunk staged exactly once more


def test_native_boundary_fires_through_hook():
    if not native.available("chunk_combiner"):
        pytest.skip("native chunk_combiner unavailable")
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 2], np.int32)

    def step(s, c):
        labels = native.cc_chunk_combine(src, dst, None, 4)
        return np.int64(s + labels[0] + c), None

    plan = faults.FaultPlan([faults.Fault("native", at=1, count=1)])
    with faults.install(plan):
        r = ResilientRunner(step, list(range(4)), np.int64(0),
                            config=_fast())
        r.run()
    assert plan.calls("native") >= 4
    assert r.stats["retries"] == 1


def test_repeated_native_errors_degrade_to_fallback():
    def boom():
        e = MemoryError("native alloc failed")
        e.stem = "fake_stem"
        return e

    calls = {"native": 0, "fallback": 0}

    def native_step(s, c):
        calls["native"] += 1
        faults.inject("native")
        return _step(s, c)

    def fallback_step(s, c):
        calls["fallback"] += 1
        return _step(s, c)

    plan = faults.FaultPlan([
        faults.Fault("native", at=2, count=100, exc=boom),
    ])
    try:
        with faults.install(plan):
            r = ResilientRunner(
                native_step, list(range(8)), np.int64(0),
                config=_fast(degrade_after=2),
                fallback_step=fallback_step,
            )
            final = r.run()
        assert int(final) == int(_clean_run(8))
        assert r.stats["degraded"] is True
        assert calls["fallback"] == 6  # chunks 2..7 on the numpy path
        assert native.disabled_reason("fake_stem") is not None
        assert not native.available("fake_stem") \
            if "fake_stem" in native._AVAILABLE else True
    finally:
        native.reenable("fake_stem")


def test_bus_counters_observe_injection_matrix(tmp_path):
    # ISSUE 5 acceptance: retries, degradations, and checkpoint misses
    # in the injection matrix are observable as EVENT-BUS COUNTERS, not
    # just log lines. One run drives all three ladders — a retried step
    # fault, a native degradation, and a hung checkpoint write — and the
    # bus must count every one of them (plus the injections themselves).
    from gelly_tpu import obs

    def boom():
        e = MemoryError("native alloc failed")
        e.stem = "bus_stem"
        return e

    def native_step(s, c):
        faults.inject("native")
        return _step(s, c)

    plan = faults.FaultPlan([
        faults.Fault("step", at=1, count=1),            # retried to success
        faults.Fault("native", at=4, count=100, exc=boom),  # degrades
        faults.Fault("checkpoint_write", at=1, kind="hang",
                     hang_seconds=10.0),                # one tolerated miss
    ])
    try:
        with obs.scope() as bus:
            with faults.install(plan):
                r = ResilientRunner(
                    native_step, list(range(10)), np.int64(0),
                    checkpoint_dir=str(tmp_path),
                    config=_fast(degrade_after=2, checkpoint_every_chunks=3,
                                 watchdog_timeout=0.3),
                    fallback_step=_step,
                )
                final = r.run()
            counters = bus.snapshot()["counters"]
            gauges = bus.snapshot()["gauges"]
    finally:
        native.reenable("bus_stem")
    assert int(final) == int(_clean_run(10))
    # every ladder is countable off the bus, matching the runner's stats
    assert counters["resilience.retries"] == r.stats["retries"] >= 1
    assert counters["resilience.degradations"] == 1
    assert counters["resilience.checkpoint_misses"] \
        == r.stats["checkpoint_failures"] == 1
    # The bus counts COMPLETED writes (the hung one never completes);
    # runner stats count non-raising save() initiations — both present,
    # deliberately different currencies.
    assert counters["resilience.checkpoints"] >= 1
    assert counters["faults.injected"] == len(plan.fired) >= 4
    # durability currency rides along: bytes written + last write latency
    assert counters["resilience.checkpoint_bytes"] > 0
    assert gauges["resilience.checkpoint_write_s"] >= 0


def test_bus_counts_watchdog_fires_and_source_restarts():
    from gelly_tpu import obs

    fails = {"n": 0}

    def make_iter(pos):
        def gen():
            for i in range(pos, 8):
                if i == 5 and fails["n"] == 0:
                    fails["n"] = 1
                    raise OSError("source hiccup")
                yield i
        return gen()

    plan = faults.FaultPlan([
        faults.Fault("step", at=2, kind="hang", hang_seconds=5.0),
    ])
    with obs.scope() as bus:
        with faults.install(plan):
            r = ResilientRunner(
                _step, make_iter, np.int64(0),
                config=_fast(watchdog_timeout=0.2),
            )
            final = r.run()
        counters = bus.snapshot()["counters"]
    assert int(final) == int(_clean_run(8))
    assert counters["resilience.watchdog_timeouts"] >= 1
    assert counters["resilience.source_restarts"] == r.stats["restarts"] == 1


def test_source_failure_restarts_without_loss():
    fails = {"n": 0}

    def make_iter(pos):
        def gen():
            for i in range(pos, 12):
                if i == 7 and fails["n"] == 0:
                    fails["n"] = 1
                    raise OSError("source hiccup")
                yield i
        return gen()

    r = ResilientRunner(_step, make_iter, np.int64(0), config=_fast())
    emitted = [c for _, c in r.emissions()]
    assert emitted == list(range(12))  # no loss, no duplicates
    assert int(r.state) == int(_clean_run(12))
    assert r.stats["restarts"] == 1


def test_checkpoint_write_fault_retried_inside_manager(tmp_path):
    plan = faults.FaultPlan([
        faults.Fault("checkpoint_write", at=0, count=1,
                     exc=lambda: OSError("EIO")),
    ])
    with faults.install(plan):
        r = ResilientRunner(
            _step, list(range(6)), np.int64(0),
            checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=2),
        )
        final = r.run()
    assert int(final) == int(_clean_run(6))
    _, pos, _ = load_checkpoint(
        os.path.join(tmp_path, "ckpt-000000000006.npz"), like=np.int64(0)
    )
    assert pos == 6


def test_time_based_checkpoint_cadence(tmp_path):
    fake = {"t": 0.0}

    def step_tick(s, c):
        fake["t"] += 1.0  # each chunk "takes" one fake second
        return _step(s, c)

    r = ResilientRunner(
        step_tick, list(range(9)), np.int64(0),
        checkpoint_dir=str(tmp_path),
        config=_fast(
            checkpoint_every_chunks=10 ** 9,  # count cadence never fires
            checkpoint_every_seconds=3.0,
            clock=lambda: fake["t"],
        ),
    )
    final = r.run()
    assert int(final) == int(_clean_run(9))
    # T-second cadence: checkpoints at fake-times 3, 6, 9 → positions
    # 3/6/9, plus the forced end-of-stream write is already position 9.
    mgr = CheckpointManager(str(tmp_path))
    positions = [int(os.path.basename(p)[5:-4]) for p in mgr.list()]
    assert positions == [3, 6, 9]


def test_hung_checkpoint_write_degrades_then_recovers(tmp_path):
    # ONE hung write: the fold must tolerate the missed checkpoint (log +
    # continue, durability degraded) and finish with the final state
    # durable — a healthy multi-hour run must not die for one slow disk.
    plan = faults.FaultPlan([
        faults.Fault("checkpoint_write", at=1, kind="hang",
                     hang_seconds=10.0),
    ])
    t0 = time.monotonic()
    with faults.install(plan):
        r = ResilientRunner(
            _step, list(range(10)), np.int64(0),
            checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=2, watchdog_timeout=0.3),
        )
        final = r.run()
    assert time.monotonic() - t0 < 5.0  # never sat out the 10s hang
    assert int(final) == int(_clean_run(10))
    assert r.stats["checkpoint_failures"] == 1
    mgr = CheckpointManager(str(tmp_path))
    state, pos, _, _ = mgr.load_latest(like=np.int64(0))
    assert pos == 10  # end-of-stream checkpoint is durable


def test_persistently_hung_checkpoint_writes_abort(tmp_path):
    # EVERY write hangs: after max_checkpoint_failures consecutive misses
    # the run aborts with the watchdog error instead of silently folding
    # on with no durability at all.
    plan = faults.FaultPlan([
        faults.Fault("checkpoint_write", at=1, count=10 ** 6, kind="hang",
                     hang_seconds=10.0),
    ])
    t0 = time.monotonic()
    with faults.install(plan):
        r = ResilientRunner(
            _step, list(range(40)), np.int64(0),
            checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=2, watchdog_timeout=0.2,
                         max_checkpoint_failures=2),
        )
        with pytest.raises(WatchdogTimeout) as ei:
            r.run()
    assert ei.value.boundary == "checkpoint_write"
    assert time.monotonic() - t0 < 8.0
    assert r.stats["checkpoint_failures"] == 2


def test_checkpoint_read_fault_falls_back_to_previous(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    mgr.save(np.int64(1), 1)
    mgr.save(np.int64(2), 2)
    plan = faults.FaultPlan([faults.Fault("checkpoint_read", at=0)])
    with faults.install(plan):
        state, pos, _, _ = mgr.load_latest(like=np.int64(0))
    assert pos == 1 and int(state) == 1  # newest unreadable -> previous


# ---------------------------------------------------------------------- #
# exactly-once resume


def _interrupt_then_resume(tmp_path, n, crash_at, **runner_kw):
    """Run with a hard (non-retryable) fault at chunk ``crash_at``, then
    resume a fresh runner; returns (resumed_runner, final_state)."""
    plan = faults.FaultPlan([
        faults.Fault("step", at=crash_at, count=100, retryable=False),
    ])
    with faults.install(plan):
        r1 = ResilientRunner(
            _step, list(range(n)), np.int64(0),
            checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=3), **runner_kw,
        )
        with pytest.raises(faults.FaultInjected):
            r1.run()
    r2 = ResilientRunner(
        _step, list(range(n)), np.int64(0),
        checkpoint_dir=str(tmp_path),
        config=_fast(checkpoint_every_chunks=3), **runner_kw,
    )
    return r2, r2.run()


def test_resume_is_bit_identical_to_uninterrupted(tmp_path):
    r2, final = _interrupt_then_resume(tmp_path, n=20, crash_at=11)
    assert r2.stats["resumed_from"] is not None
    assert r2.stats["chunks"] < 20  # genuinely skipped the folded prefix
    want = _clean_run(20)
    assert int(final) == int(want)
    assert np.asarray(final).dtype == want.dtype


def test_resume_survives_torn_newest_checkpoint(tmp_path):
    plan = faults.FaultPlan([
        faults.Fault("step", at=11, count=100, retryable=False),
        # Tear every checkpoint written from the 3rd on — the newest files
        # on disk at crash time are garbage; resume must walk back to the
        # last intact one.
        faults.Fault("checkpoint_corrupt", at=2, count=100, kind="corrupt"),
    ])
    with faults.install(plan):
        r1 = ResilientRunner(
            _step, list(range(20)), np.int64(0),
            checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=2, keep_checkpoints=4),
        )
        with pytest.raises(faults.FaultInjected):
            r1.run()
    r2 = ResilientRunner(
        _step, list(range(20)), np.int64(0),
        checkpoint_dir=str(tmp_path), config=_fast(),
    )
    final = r2.run()
    assert int(final) == int(_clean_run(20))


def test_resume_with_edge_stream_cc_fold(tmp_path):
    """The real contract: a jitted CC fold over an EdgeStream, interrupted
    and resumed, matches the uninterrupted summary bit-for-bit."""
    import jax

    from gelly_tpu import edge_stream_from_edges
    from gelly_tpu.library.connected_components import connected_components

    rng = np.random.default_rng(3)
    edges = [(int(a), int(b)) for a, b in rng.integers(0, 64, (512, 2))]

    def stream():
        return edge_stream_from_edges(edges, vertex_capacity=64,
                                      chunk_size=16)

    agg = connected_components(64)
    fold = jax.jit(agg.fold)
    step = lambda s, c: (fold(s, c), None)  # noqa: E731

    clean = ResilientRunner(step, stream(), agg.init, config=_fast()).run()

    plan = faults.FaultPlan([
        faults.Fault("step", at=20, count=100, retryable=False),
    ])
    with faults.install(plan):
        r1 = ResilientRunner(
            step, stream(), agg.init, checkpoint_dir=str(tmp_path),
            config=_fast(checkpoint_every_chunks=4),
        )
        with pytest.raises(faults.FaultInjected):
            r1.run()
    r2 = ResilientRunner(
        step, stream(), agg.init, checkpoint_dir=str(tmp_path),
        config=_fast(checkpoint_every_chunks=4),
    )
    resumed = r2.run()
    assert r2.stats["resumed_from"] is not None
    for a, b in zip(jax.tree.leaves(clean), jax.tree.leaves(resumed)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# ---------------------------------------------------------------------- #
# kill -9 crash recovery (subprocess)


CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_crash_child.py")


def _spawn_child(ckpt_dir, out, sleep_s):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single default CPU device is enough
    return subprocess.Popen(
        [sys.executable, CHILD, str(ckpt_dir), str(out), str(sleep_s)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def test_kill9_recovery_bit_identical(tmp_path):
    ckpt = tmp_path / "ckpt"
    out_resumed = tmp_path / "resumed.npz"
    out_clean = tmp_path / "clean.npz"

    # Uninterrupted reference run (no checkpointing, full speed).
    clean_dir = tmp_path / "ckpt_clean"
    p = _spawn_child(clean_dir, out_clean, 0.0)
    assert p.wait(timeout=300) == 0

    # Run 1: throttled so checkpoints land mid-stream; SIGKILL once at
    # least two checkpoints exist (the newest might be mid-write).
    p = _spawn_child(ckpt, out_resumed, 0.05)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if p.poll() is not None:
            pytest.fail(f"child exited early (rc={p.returncode}) before kill")
        ckpts = sorted(ckpt.glob("ckpt-*.npz"))
        if len(ckpts) >= 2:
            break
        time.sleep(0.02)
    else:
        pytest.fail("no checkpoints appeared before the deadline")
    os.kill(p.pid, signal.SIGKILL)
    assert p.wait(timeout=60) == -signal.SIGKILL
    assert not out_resumed.exists()  # truly died mid-stream
    import _crash_child

    total_chunks = _crash_child.build_stream().source.num_chunks
    top = int(sorted(ckpt.glob("ckpt-*.npz"))[-1].stem.split("-")[1])
    assert top < total_chunks  # checkpointed position is mid-stream

    # Run 2: same command, resumes from the newest valid checkpoint.
    p = _spawn_child(ckpt, out_resumed, 0.0)
    assert p.wait(timeout=300) == 0

    resumed, pos_r, _ = load_checkpoint(str(out_resumed))
    clean, pos_c, _ = load_checkpoint(str(out_clean))
    assert pos_r == pos_c == total_chunks
    assert len(resumed) == len(clean)
    for a, b in zip(resumed, clean):
        assert a.tobytes() == b.tobytes()  # bit-identical summary


# ---------------------------------------------------------------------- #
# review regressions


def test_single_shot_iterator_restart_fails_loudly():
    # A generator source can be folded once, but a source restart must NOT
    # silently re-read the exhausted iterator and "succeed" with data
    # missing — it raises an actionable StreamFault instead.
    from gelly_tpu.engine.resilience import StreamFault

    def gen():
        yield from range(5)

    r = ResilientRunner(_step, gen(), np.int64(0), config=_fast())
    assert int(r.run()) == int(_clean_run(5))  # one pass works

    def gen_flaky():
        yield 0
        yield 1
        raise OSError("transient mid-stream")

    r2 = ResilientRunner(_step, gen_flaky(), np.int64(0), config=_fast())
    with pytest.raises(StreamFault, match="single-shot"):
        r2.run()


def test_load_latest_survives_header_meta_damage(tmp_path):
    # Header damage around the 'meta' key must never escape as a raw
    # KeyError/TypeError from load_latest: a MISSING meta is benign (the
    # CRC-verified payload is intact — load with {}), a WRONG-TYPED meta
    # is corruption (fall back to the previous checkpoint).
    import json

    def rewrite(path, mutate):
        with np.load(path) as z:
            header = json.loads(bytes(z["__header__"]).decode())
            arrays = {k: z[k] for k in z.files if k != "__header__"}
        mutate(header)
        with open(path, "wb") as f:
            np.savez(f, __header__=np.frombuffer(
                json.dumps(header).encode(), dtype=np.uint8
            ), **arrays)

    mgr = CheckpointManager(str(tmp_path), async_write=False)
    mgr.save(np.int64(1), 1)
    mgr.save(np.int64(2), 2)
    newest = mgr.list()[-1]

    rewrite(newest, lambda h: h.pop("meta"))
    state, pos, meta, _ = mgr.load_latest(like=np.int64(0))
    assert pos == 2 and int(state) == 2 and meta == {}

    rewrite(newest, lambda h: h.__setitem__("meta", "garbage"))
    state, pos, _, _ = mgr.load_latest(like=np.int64(0))
    assert pos == 1 and int(state) == 1  # fell back, no raw exception


# ---------------------------------------------------------------------- #
# concurrency regression (racecheck RC002 fix): consecutive_failures is
# bumped from the async writer daemon AND from flush() on the driver


@pytest.mark.racecheck
def test_checkpoint_failure_accounting_is_exact_under_contention(
        tmp_path, monkeypatch):
    """Pre-fix the unlocked ``consecutive_failures += 1`` lost updates
    when writer-thread failures raced flush()'s timeout accounting —
    under-counting misses inflates the max_checkpoint_failures budget.
    Post-fix the count is exact."""
    import threading as _threading

    from gelly_tpu.engine import resilience as res_mod

    def failing_save(*a, **kw):
        raise ValueError("disk on fire")  # permanent: no retry sleeps

    monkeypatch.setattr(res_mod, "save_checkpoint", failing_save)
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    n_threads, per_thread = 8, 25

    def hammer():
        for i in range(per_thread):
            with pytest.raises(ValueError):
                mgr._write({}, i, None)

    threads = [_threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert mgr.consecutive_failures == n_threads * per_thread


# ---------------------------------------------------------------------- #
# barrier watchdog budget (ISSUE 8 satellite): the coordination watchdog
# must budget watchdog_timeout + 2 * barrier_timeout, so the protocol's
# own missing/dead-host diagnosis always fires before a generic
# WatchdogTimeout masks it — and a refactor cannot silently shrink the
# budget below the protocol's own timeout.


def _tmp_coordinator(tmp_path, **cfg_kw):
    from gelly_tpu.engine.coordination import (
        CoordinationConfig, Coordinator, HostIdentity,
    )

    cfg_kw.setdefault("lease_thread", False)
    return Coordinator(str(tmp_path), HostIdentity(0, 1),
                       CoordinationConfig(**cfg_kw))


@pytest.mark.racecheck
def test_barrier_watchdog_budget_formula(tmp_path):
    co = _tmp_coordinator(tmp_path / "a", barrier_timeout=7.0)
    try:
        r = ResilientRunner(
            _step, [1], np.int64(0), coordinator=co,
            config=ResilienceConfig(watchdog_timeout=3.0),
        )
        assert r._barrier_watchdog.timeout == 3.0 + 2 * 7.0
        assert r._barrier_watchdog.timeout > co.config.barrier_timeout
    finally:
        co.close()
    # watchdog disabled -> barrier watchdog disabled too (never a
    # smaller budget than the plain boundaries)
    co2 = _tmp_coordinator(tmp_path / "b", barrier_timeout=7.0)
    try:
        r2 = ResilientRunner(
            _step, [1], np.int64(0), coordinator=co2,
            config=ResilienceConfig(watchdog_timeout=None),
        )
        assert r2._barrier_watchdog.timeout is None
    finally:
        co2.close()
    # no coordinator: the barrier watchdog is inert
    r3 = ResilientRunner(_step, [1], np.int64(0))
    assert r3._barrier_watchdog.timeout is None


def test_barrier_hang_within_budget_survives_the_watchdog(tmp_path):
    """A FaultPlan hang on the ``barrier`` boundary longer than the
    plain watchdog_timeout but inside the documented
    ``watchdog + 2*barrier_timeout`` budget must complete, not raise
    WatchdogTimeout — the bound is load-bearing, not decorative."""
    # Control: the plain watchdog WOULD have fired on this hang.
    with pytest.raises(WatchdogTimeout):
        Watchdog(0.05).call(lambda: time.sleep(0.2), "control")

    co = _tmp_coordinator(tmp_path, barrier_timeout=0.5, lease_ttl=2.0,
                          poll_s=0.005)
    plan = faults.FaultPlan(
        [faults.Fault("barrier", at=0, kind="hang", hang_seconds=0.2)]
    )
    with faults.install(plan):
        r = ResilientRunner(
            _step, [1, 2, 3, 4], np.int64(0), coordinator=co,
            config=ResilienceConfig(checkpoint_every_chunks=2,
                                    watchdog_timeout=0.05),
        )
        final = r.run()
    assert ("barrier", 0, "hang") in plan.fired
    assert int(final) == ((((0 * 3 + 1) * 3 + 2) * 3 + 3) * 3 + 4)
    assert r.stats["checkpoints"] >= 1
