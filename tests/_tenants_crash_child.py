"""Subprocess body for the multi-tenant kill -9 crash test
(test_tenants.py).

Runs a MultiTenantEngine over T deterministic tenant streams with
per-tenant checkpoints, throttled (sleep per source chunk) so the
parent's SIGKILL lands mid-window with tenants at different positions.
The second incarnation resumes every tenant from its own newest valid
``t<tid>-<pos>.npz`` rotation and must produce final labels
bit-identical to an unkilled run — proving the per-tenant
last-dispatched-chunk position rule.

argv: <checkpoint_dir> <out_npz> [chunk_sleep_seconds]
Env: GELLY_TEN_TENANTS / _EDGES / _NV / _CHUNK override the shape.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gelly_tpu import edge_stream_from_edges  # noqa: E402
from gelly_tpu.engine.checkpoint import save_checkpoint  # noqa: E402
from gelly_tpu.engine.tenants import MultiTenantEngine  # noqa: E402
from gelly_tpu.library.connected_components import (  # noqa: E402
    cc_tenant_tier,
)

TENANTS = int(os.environ.get("GELLY_TEN_TENANTS", "3"))
N_EDGES = int(os.environ.get("GELLY_TEN_EDGES", "768"))
N_V = int(os.environ.get("GELLY_TEN_NV", "96"))
CHUNK = int(os.environ.get("GELLY_TEN_CHUNK", "16"))
# GELLY_TEN_COMPRESSED=1 runs a COMPRESSED tier: the sources compress
# each chunk at the producer (the pull thread) and lanes fold the
# payloads via fold_codec — the kill must land mid-window and the
# per-tenant payload-position resume must stay exactly-once too.
COMPRESSED = os.environ.get("GELLY_TEN_COMPRESSED", "0") == "1"


def build_stream(tid: int):
    rng = np.random.default_rng(100 + tid)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    return edge_stream_from_edges(
        [(int(a), int(b)) for a, b in pairs],
        vertex_capacity=N_V, chunk_size=CHUNK,
    )


def throttled(stream, sleep_s: float, compress=None):
    def gen(position: int):
        for c in stream.chunks_from(position):
            if sleep_s:
                time.sleep(sleep_s)
            yield c if compress is None else compress(c)

    return gen  # a callable position -> iterator (seekable)


def main(argv):
    ckpt_dir, out_path = argv[0], argv[1]
    sleep_s = float(argv[2]) if len(argv) > 2 else 0.0
    agg, cap = cc_tenant_tier(
        N_V, chunk_capacity=CHUNK, compressed=COMPRESSED,
        codec="sparse" if COMPRESSED else "auto",
    )
    eng = MultiTenantEngine(
        merge_every=2, checkpoint_dir=ckpt_dir, checkpoint_every=1,
        resume=True,
    )
    eng.add_tier("cc", agg, cap, compressed=COMPRESSED)
    compress = agg.host_compress if COMPRESSED else None
    for tid in range(TENANTS):
        eng.admit(tid, "cc",
                  chunks=throttled(build_stream(tid), sleep_s,
                                   compress=compress))
    out = eng.drain()
    save_checkpoint(
        out_path, [np.asarray(out[tid]) for tid in range(TENANTS)],
        position=sum(eng.position(t) for t in range(TENANTS)),
    )


if __name__ == "__main__":
    main(sys.argv[1:])
