"""Pallas VMEM-blocked gather + fold-backend tests (interpret mode).

Every kernel here runs under ``interpret=True`` on the CPU backend — the
exact code path the TPU compiles — so tier-1 exercises the Pallas fold
without hardware (the ISSUE's CI requirement). Shapes are deliberately
tiny: the interpreter executes grid steps serially in Python.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu.ops import unionfind
from gelly_tpu.ops.pallas_kernels import (
    blocked_gather,
    gatherable,
    sorted_window_gather,
)

pytestmark = pytest.mark.pallas

N = 1 << 12  # slot space of every fold test (window-blockable)


# --------------------------------------------------------------------- #
# sorted_window_gather — the microkernel


def test_sorted_gather_exact_on_sorted_uniform():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    idx = np.sort(rng.integers(0, N, 2000)).astype(np.int32)
    got = np.asarray(sorted_window_gather(table, jnp.asarray(idx), tile=512))
    want = np.asarray(table)[idx]
    assert (got >= 0).all()  # dense sorted run: every lane in-window
    assert np.array_equal(got, want)


def test_sorted_gather_hot_duplicates_and_bounds():
    # A hot slot repeated across whole tiles, plus both boundary slots.
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    idx = np.sort(np.concatenate([
        np.zeros(600, np.int32),
        np.full(900, 7, np.int32),
        np.full(3, N - 1, np.int32),
    ]))
    got = np.asarray(sorted_window_gather(table, jnp.asarray(idx), tile=512))
    want = np.asarray(table)[idx]
    hit = got >= 0
    # Misses may only appear where the run jumps windows — and a miss is
    # a -1 marker, never a wrong value.
    assert np.array_equal(got[hit], want[hit])
    assert hit.mean() > 0.9


def test_sorted_gather_piecewise_seam_marks_misses():
    # Two concatenated sorted runs: the seam tile spans the whole table,
    # so some lanes must come back -1 (unresolved), none wrong.
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    idx = np.concatenate([
        np.sort(rng.integers(N // 2, N, 512)),
        np.sort(rng.integers(0, N // 2, 512)),
    ]).astype(np.int32)
    # window_rows=4 -> a 512-slot window (1024 doubled), far below the
    # table: the seam tile cannot cover both halves.
    got = np.asarray(sorted_window_gather(
        table, jnp.asarray(idx), tile=256, window_rows=4))
    want = np.asarray(table)[idx]
    hit = got >= 0
    assert np.array_equal(got[hit], want[hit])
    assert not hit.all()  # the seam must be flagged, not fabricated


def test_sorted_gather_rejects_unblockable_table():
    with pytest.raises(ValueError):
        sorted_window_gather(
            jnp.zeros(1000, jnp.int32), jnp.zeros(8, jnp.int32)
        )
    assert not gatherable(1000)
    assert not gatherable((1 << 24) + 128)  # above the f32-exactness bound
    assert gatherable(1 << 12) and gatherable(1 << 24)


def test_blocked_gather_exact_any_order_and_under_jit():
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    idx = rng.integers(0, N, 1500).astype(np.int32)
    want = np.asarray(table)[idx]
    got = np.asarray(blocked_gather(table, jnp.asarray(idx), tile=512))
    assert np.array_equal(got, want)
    f = jax.jit(lambda t, i: blocked_gather(t, i, tile=512))
    assert np.array_equal(np.asarray(f(table, jnp.asarray(idx))), want)
    # Unblockable table: silently falls back to the plain XLA gather.
    t2 = jnp.asarray(rng.integers(0, 100, 100).astype(np.int32))
    i2 = rng.integers(0, 100, 64).astype(np.int32)
    assert np.array_equal(
        np.asarray(blocked_gather(t2, jnp.asarray(i2))), np.asarray(t2)[i2]
    )
    # Values beyond the f32-exact bound (hashes, not parent ids): the
    # runtime value guard must fall back to the exact plain gather
    # instead of returning f32-rounded neighbors.
    t3 = jnp.asarray(
        (rng.integers(0, 1 << 30, N) | 1).astype(np.int32))  # odd, > 2^24
    got3 = np.asarray(blocked_gather(t3, jnp.asarray(idx), tile=512))
    assert np.array_equal(got3, np.asarray(t3)[idx])


# --------------------------------------------------------------------- #
# union_edges_dedup backend parity — adversarial streams


def _oracle_labels(chunks, n):
    """Python DSU over the whole stream: canonical min-slot labels."""
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    seen = set()
    for src, dst, valid in chunks:
        for u, v, ok in zip(src.tolist(), dst.tolist(), valid.tolist()):
            if not ok:
                continue
            seen.update((u, v))
            ru, rv = find(u), find(v)
            if ru != rv:
                parent[max(ru, rv)] = min(ru, rv)
    return np.array(
        [find(i) if i in seen or parent[i] != i else i for i in range(n)],
        np.int32,
    )


_FOLD_CACHE: dict = {}


def _fold_stream(chunks, backend, unique_cap, tail_cap=None):
    # One jitted fold per (backend, caps): the adversarial streams share
    # shapes, so reusing the executable keeps the tier-1 budget flat.
    key = (backend, unique_cap, tail_cap)
    if key not in _FOLD_CACHE:
        _FOLD_CACHE[key] = jax.jit(
            lambda p, s, d, v: unionfind.union_edges_dedup(
                p, s, d, v, unique_cap=unique_cap, tail_cap=tail_cap,
                backend=backend, interpret=True,
            )
        )
    fold = _FOLD_CACHE[key]
    p = unionfind.fresh_forest(N)
    for src, dst, valid in chunks:
        p = fold(p, jnp.asarray(src), jnp.asarray(dst), jnp.asarray(valid))
    return np.asarray(unionfind.pointer_jump(p))


def _adversarial_streams():
    rng = np.random.default_rng(7)
    E = 1024
    ones = np.ones(E, bool)
    # hot vertex: half of all edges touch slot 3 (plus self-loops on it)
    hot_s = np.where(rng.random(E) < 0.5, 3, rng.integers(0, N, E))
    hot_d = rng.integers(0, N, E)
    hot_d[::17] = hot_s[::17]  # self-loops
    # already-rooted pairs: the same chunk folded twice (second fold is
    # all no-op unions against an already-built forest)
    rep_s = rng.integers(0, N, E)
    rep_d = rng.integers(0, N, E)
    # chain merges: a long path unioned in shuffled order across chunks
    perm = rng.permutation(2 * E)
    order = rng.permutation(2 * E - 1)
    ch_s = perm[:-1][order]
    ch_d = perm[1:][order]
    # masked lanes mixed with duplicates
    mk_s = rng.integers(0, N, E)
    mk_d = np.concatenate([mk_s[: E // 2], rng.integers(0, N, E // 2)])
    mask = rng.random(E) > 0.4
    return {
        "hot-vertex+self-loops": [
            (hot_s.astype(np.int32), hot_d.astype(np.int32), ones)
        ],
        "already-rooted-repeat": [
            (rep_s.astype(np.int32), rep_d.astype(np.int32), ones),
            (rep_s.astype(np.int32), rep_d.astype(np.int32), ones),
        ],
        "chain-merge": [
            (ch_s[:E].astype(np.int32), ch_d[:E].astype(np.int32), ones),
            (ch_s[E:].astype(np.int32),
             ch_d[E:].astype(np.int32), ones[: E - 1]),
        ],
        "masked-duplicates": [
            (mk_s.astype(np.int32), mk_d.astype(np.int32), mask)
        ],
    }


def test_dedup_backend_parity_on_adversarial_streams():
    for name, chunks in _adversarial_streams().items():
        want = _oracle_labels(chunks, N)
        xla = _fold_stream(chunks, "xla", unique_cap=1024)
        pal = _fold_stream(chunks, "pallas", unique_cap=1024)
        assert np.array_equal(xla, want), f"xla vs oracle: {name}"
        assert np.array_equal(pal, want), f"pallas vs oracle: {name}"


def test_dedup_backend_parity_on_cap_overflows():
    rng = np.random.default_rng(11)
    E = 512
    # all-distinct pairs overflow a tiny unique_cap (exact full-width
    # fallback); a tiny tail_cap overflows the survivor compaction.
    s = (np.arange(E, dtype=np.int32) * 2) % N
    d = ((np.arange(E, dtype=np.int32) * 2) + 1) % N
    chunks = [(s, d, np.ones(E, bool))]
    want = _oracle_labels(chunks, N)
    for ucap, tcap in ((64, None), (E, 8)):
        xla = _fold_stream(chunks, "xla", unique_cap=ucap, tail_cap=tcap)
        pal = _fold_stream(chunks, "pallas", unique_cap=ucap, tail_cap=tcap)
        assert np.array_equal(xla, want), (ucap, tcap)
        assert np.array_equal(pal, want), (ucap, tcap)
    zs = (rng.zipf(1.3, E) % N).astype(np.int32)
    zd = (rng.zipf(1.3, E) % N).astype(np.int32)
    chunks = [(zs, zd, np.ones(E, bool))]
    want = _oracle_labels(chunks, N)
    assert np.array_equal(
        _fold_stream(chunks, "pallas", unique_cap=64, tail_cap=8), want
    )


def test_dedup_pallas_rejects_unblockable_capacity():
    with pytest.raises(ValueError, match="pallas"):
        unionfind.union_edges_dedup(
            jnp.arange(1000, dtype=jnp.int32),
            jnp.zeros(8, jnp.int32), jnp.zeros(8, jnp.int32),
            jnp.ones(8, bool), unique_cap=8, backend="pallas",
        )
    with pytest.raises(ValueError, match="backend"):
        unionfind.union_edges_dedup(
            unionfind.fresh_forest(N), jnp.zeros(8, jnp.int32),
            jnp.zeros(8, jnp.int32), jnp.ones(8, bool), unique_cap=8,
            backend="bogus",
        )


# --------------------------------------------------------------------- #
# plan knob wiring — library + engine


def _cc_module():
    import importlib

    return importlib.import_module("gelly_tpu.library.connected_components")


def test_cc_fold_backend_knob_end_to_end(monkeypatch):
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable

    ccmod = _cc_module()
    # Drop the dedup threshold so CI-sized chunks run the kernel path.
    monkeypatch.setattr(ccmod, "RAW_DEDUP_MIN_CHUNK", 256)
    rng = np.random.default_rng(13)
    E = 2048
    src = (rng.zipf(1.3, E) % N).astype(np.int32)
    dst = (rng.zipf(1.3, E) % N).astype(np.int32)

    def labels(backend):
        stream = edge_stream_from_source(
            EdgeChunkSource(src, dst, chunk_size=512,
                            table=IdentityVertexTable(N)), N)
        agg = ccmod.connected_components(
            N, merge="gather", ingest_combine=False, fold_backend=backend)
        assert agg.fold_backend == ("pallas" if backend == "pallas" else "xla")
        return np.asarray(stream.aggregate(agg, merge_every=4).result())

    assert np.array_equal(labels("xla"), labels("pallas"))


def test_cc_fold_backend_validation():
    ccmod = _cc_module()
    with pytest.raises(ValueError, match="pallas"):
        ccmod.connected_components(1000, fold_backend="pallas")
    with pytest.raises(ValueError, match="fold_backend"):
        ccmod.connected_components(N, fold_backend="bogus")
    # auto resolves to xla until the measured sweep flips it
    assert ccmod.connected_components(N).fold_backend == "xla"


def test_engine_plan_cache_keys_on_fold_backend():
    from gelly_tpu.engine import aggregation as agg_mod
    from gelly_tpu.parallel import mesh as mesh_lib

    ccmod = _cc_module()
    agg = ccmod.connected_components(N, merge="gather", ingest_combine=False)
    m = mesh_lib.make_mesh()
    agg_mod._compiled_plan(agg, m)
    # A rebuilt-for-pallas plan must not reuse the xla executables: the
    # cache key carries fold_backend (jit is lazy, so this is cheap).
    agg.fold_backend = "pallas"
    agg_mod._compiled_plan(agg, m)
    assert len(agg._plan_cache) == 2
    # Key layout: (device ids, axis names, fold_backend, merge_mode).
    assert {k[2] for k in agg._plan_cache} == {"xla", "pallas"}
    assert {k[3] for k in agg._plan_cache} == {"auto"}
