"""Subprocess body for the kill -9 crash-recovery test (test_resilience.py).

Runs a checkpointed resilient CC fold over a deterministic random edge
stream. The parent SIGKILLs this process mid-stream on the first run, then
re-runs it; the second incarnation resumes from the newest valid checkpoint
and must write a final summary bit-identical to an uninterrupted run.

argv: <checkpoint_dir> <out_npz> [chunk_sleep_seconds]
Env: GELLY_CRASH_EDGES / _NV / _CHUNK override the stream shape.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from gelly_tpu import edge_stream_from_edges  # noqa: E402
from gelly_tpu.engine.checkpoint import save_checkpoint  # noqa: E402
from gelly_tpu.engine.resilience import (  # noqa: E402
    ResilienceConfig,
    ResilientRunner,
)
from gelly_tpu.library.connected_components import (  # noqa: E402
    connected_components,
)

N_EDGES = int(os.environ.get("GELLY_CRASH_EDGES", "2048"))
N_V = int(os.environ.get("GELLY_CRASH_NV", "128"))
CHUNK = int(os.environ.get("GELLY_CRASH_CHUNK", "32"))


def build_stream():
    rng = np.random.default_rng(7)
    pairs = rng.integers(0, N_V, (N_EDGES, 2))
    edges = [(int(a), int(b)) for a, b in pairs]
    return edge_stream_from_edges(
        edges, vertex_capacity=N_V, chunk_size=CHUNK
    )


def main(argv):
    ckpt_dir, out_path = argv[0], argv[1]
    sleep_s = float(argv[2]) if len(argv) > 2 else 0.0
    agg = connected_components(N_V)
    fold = jax.jit(agg.fold)

    def step(s, c):
        if sleep_s:
            time.sleep(sleep_s)
        return fold(s, c), None

    runner = ResilientRunner(
        step,
        build_stream(),
        agg.init,
        checkpoint_dir=ckpt_dir,
        config=ResilienceConfig(
            checkpoint_every_chunks=4, watchdog_timeout=None
        ),
    )
    final = jax.device_get(runner.run())
    # Reuse the checkpoint writer as the result format (CRC-verified load
    # in the parent).
    save_checkpoint(out_path, final, position=runner.position)


if __name__ == "__main__":
    main(sys.argv[1:])
