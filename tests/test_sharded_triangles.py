"""Mesh-sharded exact triangle counting (VERDICT r3 item 7): parity vs
the single-device SparseExactTriangleStream on the 8-virtual-device CPU
mesh — per-vertex local counts AND the global total (the -1 key), with
vertex-striped adjacency state (capacity/S rows per device)."""

import numpy as np
import pytest

from gelly_tpu.core.io import EdgeChunkSource
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable
from gelly_tpu.library.sharded_triangles import ShardedExactTriangles
from gelly_tpu.library.triangles import exact_triangle_count
from gelly_tpu.parallel import mesh as mesh_lib

N_V = 256


def _stream(src, dst, chunk_size=64, n_v=N_V):
    return edge_stream_from_source(
        EdgeChunkSource(src, dst, chunk_size=chunk_size,
                        table=IdentityVertexTable(n_v)),
        n_v,
    )


def _rand(n_e, seed):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, N_V, n_e).astype(np.int64),
            rng.integers(0, N_V, n_e).astype(np.int64))


@pytest.mark.slow  # tier-1 budget: runs in the CI heavy lane
@pytest.mark.parametrize("seed", [1, 2])
def test_sharded_exact_parity_random(seed):
    src, dst = _rand(1500, seed)
    want = exact_triangle_count(
        _stream(src, dst), max_degree=N_V
    ).final_counts()
    got = ShardedExactTriangles(
        _stream(src, dst), max_degree=N_V
    ).run().final_counts()
    assert got == want


def test_sharded_exact_known_graph():
    # Two triangles sharing edge (0,1): counts 0:2, 1:2, 2:1, 3:1, total 2;
    # duplicates and self-loops ignored; cross-chunk arrivals honored.
    edges = [(0, 1), (1, 2), (2, 0), (0, 3), (3, 1), (2, 2), (0, 1)]
    src = np.array([e[0] for e in edges], np.int64)
    dst = np.array([e[1] for e in edges], np.int64)
    got = ShardedExactTriangles(
        _stream(src, dst, chunk_size=2), max_degree=8
    ).run().final_counts()
    assert got == {-1: 2, 0: 2, 1: 2, 2: 1, 3: 1}


def test_sharded_exact_state_is_striped():
    src, dst = _rand(200, 5)
    t = ShardedExactTriangles(_stream(src, dst), max_degree=16)
    assert t.nbr.shape == (8, N_V // 8, 16)
    t.run()
    assert t.nbr.shape == (8, N_V // 8, 16)


def test_sharded_exact_overflow_raises():
    star = [(0, i) for i in range(1, 30)]
    src = np.array([e[0] for e in star], np.int64)
    dst = np.array([e[1] for e in star], np.int64)
    with pytest.raises(ValueError, match="max_degree"):
        ShardedExactTriangles(_stream(src, dst), max_degree=4).run()


def test_sharded_exact_small_mesh():
    src, dst = _rand(600, 9)
    want = exact_triangle_count(
        _stream(src, dst), max_degree=N_V
    ).final_counts()
    got = ShardedExactTriangles(
        _stream(src, dst), max_degree=N_V, mesh=mesh_lib.make_mesh(2)
    ).run().final_counts()
    assert got == want
