"""Checkpoint/resume: the summary IS the checkpoint payload (SURVEY.md §5)."""

import json
import zlib

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine.checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointCorruptError,
    load_checkpoint,
    save_checkpoint,
)
from gelly_tpu.library.connected_components import (
    connected_components,
    labels_to_components,
)

CC_EDGES = [(1, 2), (1, 3), (2, 3), (1, 5), (6, 7), (8, 9)]
CC_EXPECTED = [[1, 2, 3, 5], [6, 7], [8, 9]]


def test_save_load_roundtrip(tmp_path):
    from gelly_tpu.library.connected_components import CCSummary

    agg = connected_components(32)
    s = agg.init()
    p = str(tmp_path / "ck.npz")
    save_checkpoint(p, s, position=7, meta={"k": "v"})
    loaded, pos, meta = load_checkpoint(p, like=agg.init())
    assert pos == 7 and meta == {"k": "v"}
    assert isinstance(loaded, CCSummary)
    np.testing.assert_array_equal(np.asarray(loaded.parent), np.asarray(s.parent))


def test_resume_continues_cc(tmp_path):
    p = str(tmp_path / "cc.npz")

    def stream():
        return edge_stream_from_edges(
            [(a, b, 1.0) for a, b in CC_EDGES], vertex_capacity=64,
            chunk_size=2,
        )

    s1 = stream()
    agg = connected_components(64)
    # Run the full stream once with per-window checkpoints.
    final = s1.aggregate(agg, merge_every=1, checkpoint_path=p).result()
    assert labels_to_components(final, s1.ctx) == CC_EXPECTED

    # Resume from the checkpoint: all chunks already consumed -> the stored
    # summary alone must reproduce the final labels.
    s2 = stream()
    resumed = s2.aggregate(
        agg, merge_every=1, checkpoint_path=p, resume=True
    ).result()
    assert resumed is None  # nothing left to fold; no emission

    _, pos, meta = load_checkpoint(p, like=agg.init())
    assert pos == 3 and meta["windows"] == 3


def test_resume_midstream_matches_full_run(tmp_path):
    p = str(tmp_path / "cc_mid.npz")
    agg = connected_components(64)

    # First run: only the first 2 chunks (4 edges), checkpointing.
    s1 = edge_stream_from_edges(
        [(a, b, 1.0) for a, b in CC_EDGES[:4]], vertex_capacity=64,
        chunk_size=2,
    )
    s1.aggregate(agg, merge_every=1, checkpoint_path=p).result()

    # Resume over the full stream: chunks 1-2 skipped, chunk 3 folded.
    s2 = edge_stream_from_edges(
        [(a, b, 1.0) for a, b in CC_EDGES], vertex_capacity=64, chunk_size=2,
        table=None,
    )
    final = s2.aggregate(
        agg, merge_every=1, checkpoint_path=p, resume=True
    ).result()
    assert labels_to_components(final, s2.ctx) == CC_EXPECTED


def test_window_mode_checkpoint_is_chunk_consistent(tmp_path):
    # Regression: a chunk straddling a window boundary must not be recorded
    # as consumed before ALL its windows' edges are folded. Interrupt after
    # every prefix of the stream and confirm resume never loses edges.
    import itertools

    from gelly_tpu import TimeCharacteristic

    edges = [(1, 2), (2, 3), (4, 5), (5, 6), (7, 8), (8, 9), (1, 9)]
    ts = np.array([0, 10, 90, 120, 130, 210, 290])  # windows 0,0,0,1,1,2,2
    agg = connected_components(32)

    def stream(limit=None):
        s = edge_stream_from_edges(
            edges, vertex_capacity=32, chunk_size=2,
            time=TimeCharacteristic.EVENT, timestamps=ts,
        )
        if limit is None:
            return s
        from gelly_tpu.core.stream import EdgeStream

        src = s._chunks_fn
        return EdgeStream(lambda: itertools.islice(src(), limit), s.ctx)

    full = stream()
    expected = labels_to_components(
        full.aggregate(agg, window_ms=100).result(), full.ctx
    )

    for cut in range(1, 4):
        p = str(tmp_path / f"w{cut}.npz")
        part = stream(limit=cut)
        for _ in part.aggregate(agg, window_ms=100, checkpoint_path=p):
            pass
        s2 = stream()
        resumed = s2.aggregate(
            agg, window_ms=100, checkpoint_path=p, resume=True
        ).result()
        assert labels_to_components(resumed, s2.ctx) == expected, cut


def test_resume_midstream_codec_batched_plan(tmp_path):
    # Resume must also be exact under the default CC plan at depth: the
    # ingest codec (host_compress payloads) with fold_batch > 1 and a
    # multi-chunk merge cadence. Interrupt after a prefix, resume over the
    # full stream, compare with an uninterrupted run.
    p = str(tmp_path / "cc_codec.npz")
    rng = np.random.default_rng(41)
    n_v, n_e = 256, 3000
    edges = [(int(a), int(b), 1.0) for a, b in rng.integers(0, n_v, (n_e, 2))]

    def stream(upto=None):
        return edge_stream_from_edges(
            edges[:upto], vertex_capacity=n_v, chunk_size=128,
        )

    agg = connected_components(n_v)
    kw = dict(merge_every=4, fold_batch=4)

    want_stream = stream()
    want = labels_to_components(
        want_stream.aggregate(agg, **kw).result(), want_stream.ctx
    )

    # Interrupted prefix run: 14 chunks end in a partial merge window, so
    # the final (forced end-of-stream) checkpoint records position 14 —
    # the resumed run re-enters mid-cadence, exercising skip_until with
    # the codec's batched groups.
    stream(14 * 128).aggregate(agg, checkpoint_path=p, **kw).result()
    _, pos, _ = load_checkpoint(p, like=agg.init())
    assert pos == 14

    s2 = stream()
    final = s2.aggregate(
        agg, checkpoint_path=p, resume=True, **kw
    ).result()
    assert labels_to_components(final, s2.ctx) == want


# ---------------------------------------------------------------------- #
# v2 hardening: CRC32, schema versioning, template validation


def _rewrite_header(path, mutate):
    """Load a checkpoint npz, apply ``mutate(header_dict, arrays)``, rewrite."""
    with np.load(path) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__header__"}
    mutate(header, arrays)
    with open(path, "wb") as f:
        np.savez(f, __header__=np.frombuffer(
            json.dumps(header).encode(), dtype=np.uint8
        ), **arrays)


def test_load_rejects_wrong_leaf_shape(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.zeros(8, np.int32)}, position=1)
    with pytest.raises(CheckpointCorruptError, match="shape"):
        load_checkpoint(p, like={"a": np.zeros(16, np.int32)})


def test_load_rejects_wrong_leaf_dtype(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.zeros(8, np.int32)}, position=1)
    with pytest.raises(CheckpointCorruptError, match="dtype"):
        load_checkpoint(p, like={"a": np.zeros(8, np.int64)})


def test_load_rejects_bad_position(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.zeros(4)}, position=3)
    for bad in (-5, 2 ** 60, "7", None):
        _rewrite_header(
            p, lambda h, a, b=bad: h.__setitem__("position", b)
        )
        with pytest.raises(CheckpointCorruptError, match="position"):
            load_checkpoint(p)
    with pytest.raises(ValueError, match="position"):
        save_checkpoint(p, {"a": np.zeros(4)}, position=-1)


def test_load_detects_bitrot_via_crc(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.arange(32, dtype=np.int64)}, position=2)

    def flip(h, arrays):
        arrays["leaf_0"] = arrays["leaf_0"].copy()
        arrays["leaf_0"][5] ^= 1  # single bit flip, shape/dtype intact
    _rewrite_header(p, flip)
    with pytest.raises(CheckpointCorruptError, match="CRC"):
        load_checkpoint(p)


def test_load_detects_torn_file(tmp_path):
    import os

    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.arange(1024, dtype=np.int64)}, position=2)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointCorruptError, match="torn"):
        load_checkpoint(p)


def test_load_rejects_future_version(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.zeros(4)}, position=0)
    _rewrite_header(
        p, lambda h, a: h.__setitem__("version", CHECKPOINT_VERSION + 1)
    )
    with pytest.raises(CheckpointCorruptError, match="version"):
        load_checkpoint(p)


def test_v1_checkpoint_without_crc_still_loads(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.arange(4, dtype=np.int32)}, position=5)

    def strip_v2(h, a):
        del h["version"]
        del h["crc32"]
    _rewrite_header(p, strip_v2)
    loaded, pos, _ = load_checkpoint(
        p, like={"a": np.zeros(4, np.int32)}
    )
    assert pos == 5
    np.testing.assert_array_equal(loaded["a"], np.arange(4, dtype=np.int32))


def test_crc_roundtrip_matches_manual(tmp_path):
    p = str(tmp_path / "c.npz")
    arr = np.arange(16, dtype=np.float32)
    save_checkpoint(p, [arr], position=0)
    with np.load(p) as z:
        header = json.loads(bytes(z["__header__"]).decode())
    assert header["version"] == CHECKPOINT_VERSION
    assert header["crc32"] == [zlib.crc32(arr.tobytes())]
