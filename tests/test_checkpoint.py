"""Checkpoint/resume: the summary IS the checkpoint payload (SURVEY.md §5)."""

import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine.checkpoint import load_checkpoint, save_checkpoint
from gelly_tpu.library.connected_components import (
    connected_components,
    labels_to_components,
)

CC_EDGES = [(1, 2), (1, 3), (2, 3), (1, 5), (6, 7), (8, 9)]
CC_EXPECTED = [[1, 2, 3, 5], [6, 7], [8, 9]]


def test_save_load_roundtrip(tmp_path):
    from gelly_tpu.library.connected_components import CCSummary

    agg = connected_components(32)
    s = agg.init()
    p = str(tmp_path / "ck.npz")
    save_checkpoint(p, s, position=7, meta={"k": "v"})
    loaded, pos, meta = load_checkpoint(p, like=agg.init())
    assert pos == 7 and meta == {"k": "v"}
    assert isinstance(loaded, CCSummary)
    np.testing.assert_array_equal(np.asarray(loaded.parent), np.asarray(s.parent))


def test_resume_continues_cc(tmp_path):
    p = str(tmp_path / "cc.npz")

    def stream():
        return edge_stream_from_edges(
            [(a, b, 1.0) for a, b in CC_EDGES], vertex_capacity=64,
            chunk_size=2,
        )

    s1 = stream()
    agg = connected_components(64)
    # Run the full stream once with per-window checkpoints.
    final = s1.aggregate(agg, merge_every=1, checkpoint_path=p).result()
    assert labels_to_components(final, s1.ctx) == CC_EXPECTED

    # Resume from the checkpoint: all chunks already consumed -> the stored
    # summary alone must reproduce the final labels.
    s2 = stream()
    resumed = s2.aggregate(
        agg, merge_every=1, checkpoint_path=p, resume=True
    ).result()
    assert resumed is None  # nothing left to fold; no emission

    _, pos, meta = load_checkpoint(p, like=agg.init())
    assert pos == 3 and meta["windows"] == 3


def test_resume_midstream_matches_full_run(tmp_path):
    p = str(tmp_path / "cc_mid.npz")
    agg = connected_components(64)

    # First run: only the first 2 chunks (4 edges), checkpointing.
    s1 = edge_stream_from_edges(
        [(a, b, 1.0) for a, b in CC_EDGES[:4]], vertex_capacity=64,
        chunk_size=2,
    )
    s1.aggregate(agg, merge_every=1, checkpoint_path=p).result()

    # Resume over the full stream: chunks 1-2 skipped, chunk 3 folded.
    s2 = edge_stream_from_edges(
        [(a, b, 1.0) for a, b in CC_EDGES], vertex_capacity=64, chunk_size=2,
        table=None,
    )
    final = s2.aggregate(
        agg, merge_every=1, checkpoint_path=p, resume=True
    ).result()
    assert labels_to_components(final, s2.ctx) == CC_EXPECTED


def test_window_mode_checkpoint_is_chunk_consistent(tmp_path):
    # Regression: a chunk straddling a window boundary must not be recorded
    # as consumed before ALL its windows' edges are folded. Interrupt after
    # every prefix of the stream and confirm resume never loses edges.
    import itertools

    from gelly_tpu import TimeCharacteristic

    edges = [(1, 2), (2, 3), (4, 5), (5, 6), (7, 8), (8, 9), (1, 9)]
    ts = np.array([0, 10, 90, 120, 130, 210, 290])  # windows 0,0,0,1,1,2,2
    agg = connected_components(32)

    def stream(limit=None):
        s = edge_stream_from_edges(
            edges, vertex_capacity=32, chunk_size=2,
            time=TimeCharacteristic.EVENT, timestamps=ts,
        )
        if limit is None:
            return s
        from gelly_tpu.core.stream import EdgeStream

        src = s._chunks_fn
        return EdgeStream(lambda: itertools.islice(src(), limit), s.ctx)

    full = stream()
    expected = labels_to_components(
        full.aggregate(agg, window_ms=100).result(), full.ctx
    )

    for cut in range(1, 4):
        p = str(tmp_path / f"w{cut}.npz")
        part = stream(limit=cut)
        for _ in part.aggregate(agg, window_ms=100, checkpoint_path=p):
            pass
        s2 = stream()
        resumed = s2.aggregate(
            agg, window_ms=100, checkpoint_path=p, resume=True
        ).result()
        assert labels_to_components(resumed, s2.ctx) == expected, cut


def test_resume_midstream_codec_batched_plan(tmp_path):
    # Resume must also be exact under the default CC plan at depth: the
    # ingest codec (host_compress payloads) with fold_batch > 1 and a
    # multi-chunk merge cadence. Interrupt after a prefix, resume over the
    # full stream, compare with an uninterrupted run.
    p = str(tmp_path / "cc_codec.npz")
    rng = np.random.default_rng(41)
    n_v, n_e = 256, 3000
    edges = [(int(a), int(b), 1.0) for a, b in rng.integers(0, n_v, (n_e, 2))]

    def stream(upto=None):
        return edge_stream_from_edges(
            edges[:upto], vertex_capacity=n_v, chunk_size=128,
        )

    agg = connected_components(n_v)
    kw = dict(merge_every=4, fold_batch=4)

    want_stream = stream()
    want = labels_to_components(
        want_stream.aggregate(agg, **kw).result(), want_stream.ctx
    )

    # Interrupted prefix run: 14 chunks end in a partial merge window, so
    # the final (forced end-of-stream) checkpoint records position 14 —
    # the resumed run re-enters mid-cadence, exercising skip_until with
    # the codec's batched groups.
    stream(14 * 128).aggregate(agg, checkpoint_path=p, **kw).result()
    _, pos, _ = load_checkpoint(p, like=agg.init())
    assert pos == 14

    s2 = stream()
    final = s2.aggregate(
        agg, checkpoint_path=p, resume=True, **kw
    ).result()
    assert labels_to_components(final, s2.ctx) == want
