"""Scale / skew soak tests (VERDICT r1 weak #8).

Zipf-skewed streams at 10^5-edge scale through the windowed and
summary-aggregation pipelines, checked against vectorized host oracles.
The CC codec soak lives in test_codec.py; these cover the window path
(triangles — WindowTriangles.java semantics), the parity union-find
(BipartitenessCheck.java — a single odd cycle deep in the stream must
flip the sticky failure bit), and skewed degree streams with deletions
(DegreeDistribution.java's ±1 semantics at scale).
"""

import numpy as np
import pytest

from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
from gelly_tpu.core.stream import edge_stream_from_source
from gelly_tpu.core.vertices import IdentityVertexTable


def _zipf(rng, n, n_v):
    return (rng.zipf(1.3, n) % n_v).astype(np.int64)


def test_window_triangles_skewed_soak():
    # 60k Zipf edges, 6 windows, batched dispatch path vs a per-window
    # python set-intersection oracle. Skew concentrates edges on few hot
    # vertices — the dense-window regime the MXU kernel targets (runs on
    # the CPU backend here, same code path modulo the Pallas dispatch).
    import jax.numpy as jnp

    from gelly_tpu.library.triangles import window_triangle_counts_batched

    rng = np.random.default_rng(23)
    n_e, n_v = 60_000, 512
    src, dst = _zipf(rng, n_e, n_v), _zipf(rng, n_e, n_v)
    ts = np.arange(n_e, dtype=np.int64)
    window_ms = n_e // 6

    stream = edge_stream_from_source(
        EdgeChunkSource(src, dst, timestamps=ts, chunk_size=1 << 13,
                        table=IdentityVertexTable(n_v),
                        time=TimeCharacteristic.EVENT),
        n_v,
    )
    wins, counts = zip(*window_triangle_counts_batched(
        stream, window_ms, window_capacity=4 * window_ms, batch=4
    ))
    got = dict(zip(wins, np.asarray(jnp.stack(counts)).tolist()))

    base: dict[int, int] = {}
    for w in range(0, n_e, window_ms):
        adj: dict[int, set] = {}
        seen: set = set()
        for i in range(w, min(w + window_ms, n_e)):
            a, b = int(src[i]), int(dst[i])
            if a == b or (a, b) in seen or (b, a) in seen:
                continue
            seen.add((a, b))
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set()).add(a)
        cnt = 0
        for a, b in seen:
            lo = min(a, b)
            cnt += sum(1 for u in adj[a] & adj[b] if u < lo)
        base[w // window_ms] = cnt
    assert got == base
    assert sum(got.values()) > 0  # the soak actually exercised triangles


@pytest.mark.parametrize("conflict_at", [0.05, 0.95])
def test_bipartiteness_late_conflict_soak(conflict_at):
    # 200k-edge bipartite stream (edges only cross the two parts) with ONE
    # odd edge injected at `conflict_at` of the stream: ok must flip there
    # and stay flipped (Candidates.fail() is sticky, Candidates.java:194).
    from gelly_tpu.library.bipartiteness import bipartiteness_check

    rng = np.random.default_rng(29)
    n_e, n_v = 200_000, 1 << 14
    half = n_v // 2
    a = rng.integers(0, half, n_e)  # part A: even slots
    b = rng.integers(0, half, n_e)  # part B: odd slots
    src = (2 * a).astype(np.int64)
    dst = (2 * b + 1).astype(np.int64)
    k = int(n_e * conflict_at)
    # Odd edge: connects two part-A vertices already linked through B.
    src[k], dst[k] = src[0], 2 * rng.integers(0, half)
    if src[k] == dst[k]:
        dst[k] = (dst[k] + 2) % n_v
    # Guarantee both endpoints share a component: bridge them via B.
    src[k - 1], dst[k - 1] = src[k], 1
    src[k + 1], dst[k + 1] = dst[k], 1

    def run(n_run):
        stream = edge_stream_from_source(
            EdgeChunkSource(src[:n_run], dst[:n_run], chunk_size=1 << 14,
                            table=IdentityVertexTable(n_v)),
            n_v,
        )
        res = stream.aggregate(
            bipartiteness_check(n_v), merge_every=4, fold_batch=4
        ).result()
        return bool(res.ok)

    assert run(k - 2) is True  # clean prefix: 2-colorable
    assert run(n_e) is False  # odd cycle seen: sticky failure


def test_degree_distribution_skewed_deletions_soak():
    # 150k Zipf edges with 25% deletions through degree_aggregate's codec
    # vs a signed-bincount oracle; hot vertices reach degrees ~10^4, the
    # skew regime VERDICT flagged as untested.
    from gelly_tpu.library.degrees import degree_aggregate

    rng = np.random.default_rng(31)
    n_e, n_v = 150_000, 1 << 13
    src, dst = _zipf(rng, n_e, n_v), _zipf(rng, n_e, n_v)
    ev = (rng.random(n_e) < 0.25).astype(np.int32)

    stream = edge_stream_from_source(
        EdgeChunkSource(src, dst, events=ev, chunk_size=1 << 14,
                        table=IdentityVertexTable(n_v)),
        n_v,
    )
    got = np.asarray(stream.aggregate(
        degree_aggregate(n_v), merge_every=4, fold_batch=4
    ).result())

    sign = np.where(ev == 1, -1, 1)
    oracle = np.zeros(n_v, np.int64)
    np.add.at(oracle, src, sign)
    np.add.at(oracle, dst, sign)
    assert (got == oracle).all()
    assert int(oracle.max()) > 1000  # the skew actually materialized


def test_capped_degree_paths_at_million_vertices():
    # VERDICT r2 weak #6: the capped-degree sparse paths advertise N >= 1M
    # but had no proof at that scale. Exact sparse triangle stream AND the
    # sparse windowed kernel over n_v = 2^20 slots: memory O(N*D), counts
    # checked against a host set-intersection oracle (uniform edges keep
    # degrees under the cap; planted triangles guarantee nonzero counts).
    import jax.numpy as jnp

    from gelly_tpu.library.triangles import (
        exact_triangle_count,
        window_triangle_counts_batched,
    )

    rng = np.random.default_rng(41)
    n_v = 1 << 20
    n_bg = 120_000
    src = rng.integers(0, n_v, n_bg).astype(np.int64)
    dst = rng.integers(0, n_v, n_bg).astype(np.int64)
    # Plant triangles on random vertex triples, interleaved in the stream.
    tri = rng.integers(0, n_v, (300, 3)).astype(np.int64)
    ps = np.concatenate([tri[:, 0], tri[:, 1], tri[:, 2]])
    pd = np.concatenate([tri[:, 1], tri[:, 2], tri[:, 0]])
    order = rng.permutation(n_bg + ps.shape[0])
    src = np.concatenate([src, ps])[order]
    dst = np.concatenate([dst, pd])[order]
    n_e = src.shape[0]

    # Host oracle: global triangle count via per-edge neighbor
    # intersection over python sets.
    adj: dict[int, set] = {}
    seen = set()
    for a, b in zip(src.tolist(), dst.tolist()):
        if a == b or (a, b) in seen or (b, a) in seen:
            continue
        seen.add((a, b))
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    want_total = sum(len(adj[a] & adj[b]) for a, b in seen) // 3

    def stream(ts=None):
        kw = {}
        if ts is not None:
            kw.update(timestamps=ts, time=TimeCharacteristic.EVENT)
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, chunk_size=1 << 14,
                            table=IdentityVertexTable(n_v), **kw),
            n_v,
        )

    # Exact sparse stream (O(N*D) table at D=32: ~256 MB of i32/i64 state).
    got = exact_triangle_count(stream(), max_degree=32).final()
    assert int(got.total) == want_total and want_total >= 300

    # Sparse windowed kernel: one big window must equal the global count.
    ts = np.zeros(n_e, np.int64)
    [(w0, c0)] = list(window_triangle_counts_batched(
        stream(ts), 10, window_capacity=2 * n_e, batch=1, max_degree=32,
    ))
    assert int(c0) == want_total
