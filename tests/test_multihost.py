"""initialize_multihost exercised for real: multi-process jax.distributed
runs over the loopback coordinator (the DCN story's proof tier).

- 2-process smoke: cluster join, global mesh, psum (VERDICT r1).
- 2-process CC merge + keyed exchange (VERDICT r3).
- 4-process x 2-device tier (VERDICT r4 item 6): the first regime where
  ``hierarchical_merge``'s leader-only cross-group hop actually crosses
  process-group boundaries — butterfly AND degree-grouped hierarchical
  merges must produce oracle-identical labels, and the keyed exchange
  must conserve its multiset across 8 shards on 4 processes. The
  structural claim that the cross-group stage moves ONLY leader payloads
  is asserted on the compiled HLO in tests/test_parallel.py
  (test_hierarchical_cross_group_pairs_are_leader_only).

Each subprocess joins the cluster via
``gelly_tpu.parallel.mesh.initialize_multihost``, builds the global mesh,
and runs its body; process 0 asserts the global device count and results.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

# Shared join procedure for every worker: env pinning, repo path, and the
# cluster join. Workers are PREAMBLE + body. NPROCS/DEVS arrive via env.
_PREAMBLE = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    devs = int(os.environ.get("DEVS", "1"))
    if devs > 1:
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devs}"
        )
    else:
        os.environ.pop("XLA_FLAGS", None)
    sys.path.insert(0, os.environ["REPO_ROOT"])
    import jax
    from gelly_tpu.parallel import mesh as mesh_lib

    NP = int(os.environ["NPROCS"])
    mesh_lib.initialize_multihost(
        coordinator_address=os.environ["COORD"],
        num_processes=NP,
        process_id=int(os.environ["PID_IDX"]),
    )
    """
)

_WORKER = _PREAMBLE + textwrap.dedent(
    """
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh_lib.make_mesh()  # global mesh spanning both processes
    x = jax.make_array_from_callback(
        (2,), NamedSharding(m, P(mesh_lib.SHARD_AXIS)),
        lambda idx: jnp.asarray(
            [float(jax.process_index()) + 1.0], jnp.float32
        ),
    )
    total = jax.jit(
        lambda a: jax.numpy.sum(a), out_shardings=NamedSharding(m, P())
    )(x)
    # 1.0 (proc 0) + 2.0 (proc 1) reduced over DCN-equivalent transport.
    assert float(total) == 3.0, float(total)
    print("MULTIHOST_OK", jax.process_index())
    """
)


def test_initialize_multihost_two_processes(tmp_path):
    _run_procs(_WORKER, "MULTIHOST_OK", nprocs=2)


_CC_WORKER = _PREAMBLE + textwrap.dedent(
    """
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.library.connected_components import cc_labels_numpy
    from gelly_tpu.ops import unionfind
    from gelly_tpu.parallel import collectives

    # The deployment shape: each host folds ITS OWN edge partition
    # locally (ingest never crosses hosts), then the label forests merge
    # over the distributed transport — keyBy/window fold per host +
    # timeWindowAll fan-in across hosts (SummaryBulkAggregation.java:76-83),
    # with the fan-in as a butterfly over the global mesh.
    n_v = 64
    rng = np.random.default_rng(3)
    src = rng.integers(0, n_v, 300).astype(np.int32)
    dst = rng.integers(0, n_v, 300).astype(np.int32)
    pid = jax.process_index()
    lab = cc_labels_numpy(src[pid::2], dst[pid::2], None, n_v)
    parent = np.where(lab >= 0, lab, np.arange(n_v)).astype(np.int32)
    seen = lab >= 0

    m = mesh_lib.make_mesh()  # global mesh: one device per process
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))
    g_parent = jax.make_array_from_callback(
        (2, n_v), sh, lambda idx: jnp.asarray(parent[None, :]))
    g_seen = jax.make_array_from_callback(
        (2, n_v), sh, lambda idx: jnp.asarray(seen[None, :]))

    def merge(parent_blk, seen_blk):
        def comb(a, b):
            return (unionfind.merge_forests(a[0][0], b[0][0])[None],
                    a[1] | b[1])
        return collectives.butterfly_merge(comb, (parent_blk, seen_blk), 2)

    sh_spec = P(mesh_lib.SHARD_AXIS)
    out_parent, out_seen = mesh_lib.shard_map_fn(
        m, merge, in_specs=(sh_spec, sh_spec),
        out_specs=(sh_spec, sh_spec),
    )(g_parent, g_seen)
    got_parent = np.asarray(
        jax.device_get(out_parent.addressable_shards[0].data)
    )[0]
    got_seen = np.asarray(
        jax.device_get(out_seen.addressable_shards[0].data)
    )[0]

    # Single-process oracle over the full stream.
    full = cc_labels_numpy(src, dst, None, n_v)

    def comps(parent, seen):
        out = {}
        for v in np.nonzero(seen)[0].tolist():
            r = v
            while parent[r] != r:
                r = parent[r]
            out.setdefault(r, set()).add(v)
        return sorted(sorted(c) for c in out.values())

    got = comps(got_parent, got_seen)
    want = comps(np.where(full >= 0, full, np.arange(n_v)), full >= 0)
    assert got == want, (got[:3], want[:3])
    print("MULTIHOST_CC_OK", jax.process_index())
    """
)


def _run_procs(worker: str, token: str, nprocs: int = 2,
               devs_per_proc: int = 1, timeout: float = 240):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(nprocs):
        env = dict(
            os.environ, COORD=coord, PID_IDX=str(pid), REPO_ROOT=repo,
            JAX_PLATFORMS="cpu", NPROCS=str(nprocs),
            DEVS=str(devs_per_proc),
        )
        env.pop("XLA_FLAGS", None)
        env.pop("PYTHONPATH", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-I", "-c", worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost run timed out")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout={out}\nstderr={err}"
        assert token in out


def test_multihost_cc_merge_two_processes(tmp_path):
    # Per-host local fold + cross-host butterfly label merge == the
    # single-process result (identical final components).
    _run_procs(_CC_WORKER, "MULTIHOST_CC_OK", nprocs=2)


_EXCHANGE_WORKER = _PREAMBLE + textwrap.dedent(
    """
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.parallel import partition

    # The keyBy shuffle ACROSS PROCESSES: every entry must land on the
    # device owning its key (striped ownership), with nothing dropped —
    # the all_to_all riding the distributed transport instead of ICI.
    devs = int(os.environ.get("DEVS", "1"))
    S = NP * devs
    L = 64
    rng = np.random.default_rng(7)  # same seed everywhere: global view
    all_keys = rng.integers(0, 32, (S, L)).astype(np.int32)
    all_pay = rng.integers(0, 1000, (S, L)).astype(np.int32)
    pid = jax.process_index()

    m = mesh_lib.make_mesh()
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))

    def of_shard(arr):
        return jax.make_array_from_callback(
            (S, L), sh, lambda idx: jnp.asarray(arr[idx[0].start][None])
        )

    g_key = of_shard(all_keys)
    g_pay = of_shard(all_pay)
    g_ok = jax.make_array_from_callback(
        (S, L), sh, lambda idx: jnp.ones((1, L), bool))

    def body(k, p_, v):
        k2, p2, v2, dropped = partition.repartition_by_key(
            k[0], p_[0], v[0], S, L  # bucket = L: worst case always fits
        )
        return k2[None], p2[None], v2[None], dropped[None]

    spec = P(mesh_lib.SHARD_AXIS)
    k2, p2, v2, dropped = mesh_lib.shard_map_fn(
        m, body, in_specs=(spec,) * 3, out_specs=(spec,) * 4,
    )(g_key, g_pay, g_ok)

    # Each process checks ITS addressable shards; together the cluster
    # verifies the full multiset landed with striped ownership.
    for sk, sp, sv in zip(k2.addressable_shards, p2.addressable_shards,
                          v2.addressable_shards):
        d = sk.index[0].start
        assert sp.index[0].start == d and sv.index[0].start == d
        lk = np.asarray(jax.device_get(sk.data))[0]
        lp = np.asarray(jax.device_get(sp.data))[0]
        lv = np.asarray(jax.device_get(sv.data))[0]
        got = sorted(zip(lk[lv].tolist(), lp[lv].tolist()))
        mine = all_keys % S == d
        want = sorted(zip(all_keys[mine].tolist(), all_pay[mine].tolist()))
        assert got == want, (d, len(got), len(want))
    total_dropped = sum(
        int(np.asarray(jax.device_get(s.data)))
        for s in dropped.addressable_shards
    )
    assert total_dropped == 0
    print("MULTIHOST_EXCHANGE_OK", pid)
    """
)


def test_multihost_keyed_exchange_two_processes(tmp_path):
    # repartition_by_key's all_to_all over the cross-process transport:
    # ownership + multiset conservation, zero drops.
    _run_procs(_EXCHANGE_WORKER, "MULTIHOST_EXCHANGE_OK", nprocs=2)


# --------------------- 4-process x 2-device tier ----------------------- #

_CC4_WORKER = _PREAMBLE + textwrap.dedent(
    """
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.library.connected_components import cc_labels_numpy
    from gelly_tpu.ops import unionfind
    from gelly_tpu.parallel import collectives

    devs = int(os.environ.get("DEVS", "1"))
    S = NP * devs
    assert jax.process_count() == NP and len(jax.devices()) == S

    n_v = 64
    rng = np.random.default_rng(9)
    src = rng.integers(0, n_v, 800).astype(np.int32)
    dst = rng.integers(0, n_v, 800).astype(np.int32)

    def shard_state(idx):
        d = idx[0].start  # global shard id: folds its OWN edge partition
        lab = cc_labels_numpy(src[d::S], dst[d::S], None, n_v)
        parent = np.where(lab >= 0, lab, np.arange(n_v)).astype(np.int32)
        return jnp.asarray(parent[None, :])

    def shard_seen(idx):
        d = idx[0].start
        lab = cc_labels_numpy(src[d::S], dst[d::S], None, n_v)
        return jnp.asarray((lab >= 0)[None, :])

    m = mesh_lib.make_mesh()
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))
    g_parent = jax.make_array_from_callback((S, n_v), sh, shard_state)
    g_seen = jax.make_array_from_callback((S, n_v), sh, shard_seen)

    def comb(a, b):
        return (unionfind.merge_forests(a[0][0], b[0][0])[None],
                a[1] | b[1])

    def merge_butterfly(p_, s_):
        return collectives.butterfly_merge(comb, (p_, s_), S)

    def merge_hier(p_, s_):
        # degree = NP -> groups of `devs` consecutive shards = exactly one
        # process each: phase 1 stays intra-process (the ICI analog),
        # phase 2's leader-only exchange CROSSES process-group boundaries
        # (the DCN analog) — the regime this schedule was written for.
        return collectives.hierarchical_merge(comb, (p_, s_), S, NP)

    spec = P(mesh_lib.SHARD_AXIS)
    results = {}
    for name, fn in (("butterfly", merge_butterfly),
                     ("hierarchical", merge_hier)):
        op, os_ = mesh_lib.shard_map_fn(
            m, fn, in_specs=(spec, spec), out_specs=(spec, spec),
        )(g_parent, g_seen)
        gp = np.asarray(jax.device_get(op.addressable_shards[0].data))[0]
        gs = np.asarray(jax.device_get(os_.addressable_shards[0].data))[0]
        results[name] = (gp, gs)

    full = cc_labels_numpy(src, dst, None, n_v)

    def comps(parent, seen):
        out = {}
        for v in np.nonzero(seen)[0].tolist():
            r = v
            while parent[r] != r:
                r = parent[r]
            out.setdefault(r, set()).add(v)
        return sorted(sorted(c) for c in out.values())

    want = comps(np.where(full >= 0, full, np.arange(n_v)), full >= 0)
    for name, (gp, gs) in results.items():
        got = comps(gp, gs)
        assert got == want, (name, got[:3], want[:3])
    print("MULTIHOST_CC4_OK", jax.process_index())
    """
)


@pytest.mark.slow  # tier-1 budget: two-process twin stays in tier
def test_multihost_cc_merge_four_processes_hierarchical(tmp_path):
    """The 4-process x 2-device tier (VERDICT r4 item 6): butterfly AND
    degree-grouped hierarchical merges across FOUR process groups produce
    the single-process oracle's components. degree=4 puts each phase-1
    group exactly inside one process, so phase 2's leader hop crosses
    real process-group boundaries for the first time."""
    _run_procs(_CC4_WORKER, "MULTIHOST_CC4_OK", nprocs=4, devs_per_proc=2)


@pytest.mark.slow  # tier-1 budget: two-process twin stays in tier
def test_multihost_keyed_exchange_four_processes(tmp_path):
    """repartition_by_key across 8 shards on 4 processes: every entry
    lands on its striped owner, multiset conserved, zero drops."""
    _run_procs(_EXCHANGE_WORKER, "MULTIHOST_EXCHANGE_OK", nprocs=4,
               devs_per_proc=2)
