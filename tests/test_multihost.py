"""initialize_multihost exercised for real: a 2-process jax.distributed
smoke run over the loopback coordinator (the DCN story's minimum proof —
VERDICT r1 flagged the wrapper as never executed).

Each subprocess joins the cluster via
``gelly_tpu.parallel.mesh.initialize_multihost``, builds the global mesh,
and runs a psum over one device per process; process 0 asserts the global
device count and the reduction result.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

# Shared join procedure for every worker: env pinning, repo path, and the
# 2-process cluster join. Workers are PREAMBLE + body.
_PREAMBLE = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # exactly one local device per process
    sys.path.insert(0, os.environ["REPO_ROOT"])
    import jax
    from gelly_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_multihost(
        coordinator_address=os.environ["COORD"],
        num_processes=2,
        process_id=int(os.environ["PID_IDX"]),
    )
    """
)

_WORKER = _PREAMBLE + textwrap.dedent(
    """
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh_lib.make_mesh()  # global mesh spanning both processes
    x = jax.make_array_from_callback(
        (2,), NamedSharding(m, P(mesh_lib.SHARD_AXIS)),
        lambda idx: jnp.asarray(
            [float(jax.process_index()) + 1.0], jnp.float32
        ),
    )
    total = jax.jit(
        lambda a: jax.numpy.sum(a), out_shardings=NamedSharding(m, P())
    )(x)
    # 1.0 (proc 0) + 2.0 (proc 1) reduced over DCN-equivalent transport.
    assert float(total) == 3.0, float(total)
    print("MULTIHOST_OK", jax.process_index())
    """
)


def test_initialize_multihost_two_processes(tmp_path):
    _run_two_process(_WORKER, "MULTIHOST_OK")


_CC_WORKER = _PREAMBLE + textwrap.dedent(
    """
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.library.connected_components import cc_labels_numpy
    from gelly_tpu.ops import unionfind
    from gelly_tpu.parallel import collectives

    # The deployment shape: each host folds ITS OWN edge partition
    # locally (ingest never crosses hosts), then the label forests merge
    # over the distributed transport — keyBy/window fold per host +
    # timeWindowAll fan-in across hosts (SummaryBulkAggregation.java:76-83),
    # with the fan-in as a butterfly over the global mesh.
    n_v = 64
    rng = np.random.default_rng(3)
    src = rng.integers(0, n_v, 300).astype(np.int32)
    dst = rng.integers(0, n_v, 300).astype(np.int32)
    pid = jax.process_index()
    lab = cc_labels_numpy(src[pid::2], dst[pid::2], None, n_v)
    parent = np.where(lab >= 0, lab, np.arange(n_v)).astype(np.int32)
    seen = lab >= 0

    m = mesh_lib.make_mesh()  # global mesh: one device per process
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))
    g_parent = jax.make_array_from_callback(
        (2, n_v), sh, lambda idx: jnp.asarray(parent[None, :]))
    g_seen = jax.make_array_from_callback(
        (2, n_v), sh, lambda idx: jnp.asarray(seen[None, :]))

    def merge(parent_blk, seen_blk):
        def comb(a, b):
            return (unionfind.merge_forests(a[0][0], b[0][0])[None],
                    a[1] | b[1])
        return collectives.butterfly_merge(comb, (parent_blk, seen_blk), 2)

    sh_spec = P(mesh_lib.SHARD_AXIS)
    out_parent, out_seen = mesh_lib.shard_map_fn(
        m, merge, in_specs=(sh_spec, sh_spec),
        out_specs=(sh_spec, sh_spec),
    )(g_parent, g_seen)
    got_parent = np.asarray(
        jax.device_get(out_parent.addressable_shards[0].data)
    )[0]
    got_seen = np.asarray(
        jax.device_get(out_seen.addressable_shards[0].data)
    )[0]

    # Single-process oracle over the full stream.
    full = cc_labels_numpy(src, dst, None, n_v)

    def comps(parent, seen):
        out = {}
        for v in np.nonzero(seen)[0].tolist():
            r = v
            while parent[r] != r:
                r = parent[r]
            out.setdefault(r, set()).add(v)
        return sorted(sorted(c) for c in out.values())

    got = comps(got_parent, got_seen)
    want = comps(np.where(full >= 0, full, np.arange(n_v)), full >= 0)
    assert got == want, (got[:3], want[:3])
    print("MULTIHOST_CC_OK", jax.process_index())
    """
)


def _run_two_process(worker: str, token: str,
                     timeout: float = 120):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(
            os.environ, COORD=coord, PID_IDX=str(pid), REPO_ROOT=repo,
            JAX_PLATFORMS="cpu",
        )
        env.pop("XLA_FLAGS", None)
        env.pop("PYTHONPATH", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-I", "-c", worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost run timed out")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout={out}\nstderr={err}"
        assert token in out


def test_multihost_cc_merge_two_processes(tmp_path):
    # Per-host local fold + cross-host butterfly label merge == the
    # single-process result (identical final components).
    _run_two_process(_CC_WORKER, "MULTIHOST_CC_OK")


_EXCHANGE_WORKER = _PREAMBLE + textwrap.dedent(
    """
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gelly_tpu.parallel import partition

    # The keyBy shuffle ACROSS PROCESSES: every entry must land on the
    # device owning its key (striped ownership), with nothing dropped —
    # the all_to_all riding the distributed transport instead of ICI.
    L = 64
    rng = np.random.default_rng(7)  # same seed both processes: global view
    all_keys = rng.integers(0, 32, (2, L)).astype(np.int32)
    all_pay = rng.integers(0, 1000, (2, L)).astype(np.int32)
    pid = jax.process_index()

    m = mesh_lib.make_mesh()
    sh = NamedSharding(m, P(mesh_lib.SHARD_AXIS))
    g_key = jax.make_array_from_callback(
        (2, L), sh, lambda idx: jnp.asarray(all_keys[pid][None]))
    g_pay = jax.make_array_from_callback(
        (2, L), sh, lambda idx: jnp.asarray(all_pay[pid][None]))
    g_ok = jax.make_array_from_callback(
        (2, L), sh, lambda idx: jnp.ones((1, L), bool))

    def body(k, p_, v):
        k2, p2, v2, dropped = partition.repartition_by_key(
            k[0], p_[0], v[0], 2, L  # bucket = L: worst case always fits
        )
        return k2[None], p2[None], v2[None], dropped[None]

    spec = P(mesh_lib.SHARD_AXIS)
    k2, p2, v2, dropped = mesh_lib.shard_map_fn(
        m, body, in_specs=(spec,) * 3, out_specs=(spec,) * 4,
    )(g_key, g_pay, g_ok)

    def local(arr):
        return np.asarray(jax.device_get(arr.addressable_shards[0].data))[0]

    lk, lp, lv = local(k2), local(p2), local(v2)
    assert int(local(dropped)) == 0
    got = sorted(zip(lk[lv].tolist(), lp[lv].tolist()))
    mine = all_keys % 2 == pid
    want = sorted(zip(all_keys[mine].tolist(), all_pay[mine].tolist()))
    assert got == want, (len(got), len(want))
    print("MULTIHOST_EXCHANGE_OK", pid)
    """
)


def test_multihost_keyed_exchange_two_processes(tmp_path):
    # repartition_by_key's all_to_all over the cross-process transport:
    # ownership + multiset conservation, zero drops.
    _run_two_process(_EXCHANGE_WORKER, "MULTIHOST_EXCHANGE_OK")
