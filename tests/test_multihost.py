"""initialize_multihost exercised for real: a 2-process jax.distributed
smoke run over the loopback coordinator (the DCN story's minimum proof —
VERDICT r1 flagged the wrapper as never executed).

Each subprocess joins the cluster via
``gelly_tpu.parallel.mesh.initialize_multihost``, builds the global mesh,
and runs a psum over one device per process; process 0 asserts the global
device count and the reduction result.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # exactly one local device per process
    sys.path.insert(0, os.environ["REPO_ROOT"])
    import jax
    from gelly_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_multihost(
        coordinator_address=os.environ["COORD"],
        num_processes=2,
        process_id=int(os.environ["PID_IDX"]),
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh_lib.make_mesh()  # global mesh spanning both processes
    x = jax.make_array_from_callback(
        (2,), NamedSharding(m, P(mesh_lib.SHARD_AXIS)),
        lambda idx: jnp.asarray(
            [float(jax.process_index()) + 1.0], jnp.float32
        ),
    )
    total = jax.jit(
        lambda a: jax.numpy.sum(a), out_shardings=NamedSharding(m, P())
    )(x)
    # 1.0 (proc 0) + 2.0 (proc 1) reduced over DCN-equivalent transport.
    assert float(total) == 3.0, float(total)
    print("MULTIHOST_OK", jax.process_index())
    """
)


def test_initialize_multihost_two_processes(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(
            os.environ, COORD=coord, PID_IDX=str(pid), REPO_ROOT=repo,
            JAX_PLATFORMS="cpu",
        )
        env.pop("XLA_FLAGS", None)
        env.pop("PYTHONPATH", None)
        # -I (isolated): ignore PYTHONPATH/user-site entirely so no site
        # hook (e.g. a TPU plugin) can initialize the XLA backend before
        # jax.distributed.initialize; the worker re-adds the repo itself.
        procs.append(subprocess.Popen(
            [sys.executable, "-I", "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=90)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost smoke run timed out")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout={out}\nstderr={err}"
        assert "MULTIHOST_OK" in out
