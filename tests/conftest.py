"""Test harness: 8 virtual CPU devices — the MiniCluster analog.

The reference exercises distributed behavior with Flink's in-process
MiniCluster (multiple parallel subtasks in one JVM, SURVEY.md §4 tier 2).
Here we force the JAX CPU backend with 8 virtual devices so shard_map /
collective paths run multi-device without TPU hardware.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The environment boots a single-chip TPU platform at interpreter start and
# pins jax_platforms to it; the config update (post-import, pre-device-init)
# wins and forces the 8-virtual-device CPU backend for the test mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


# Canonical 5-vertex / 7-edge fixture used across the reference's operation
# tests (T/test/GraphStreamTestUtils.java:29-68): edges (1,2,12) ... (5,1,51).
REFERENCE_EDGES = [
    (1, 2, 12.0),
    (1, 3, 13.0),
    (2, 3, 23.0),
    (3, 4, 34.0),
    (3, 5, 35.0),
    (4, 5, 45.0),
    (5, 1, 51.0),
]


@pytest.fixture
def reference_edges():
    return list(REFERENCE_EDGES)
