"""Engine + streaming CC end-to-end: the minimum end-to-end slice.

Parity oracle: the reference's ConnectedComponentsTest
(T/example/test/ConnectedComponentsTest.java:54-63) — edges
(1,2),(1,3),(2,3),(1,5),(6,7),(8,9) → components {1,2,3,5},{6,7},{8,9}.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gelly_tpu import edge_stream_from_edges
from gelly_tpu.engine.aggregation import (
    SummaryAggregation,
    edges_fold_adapter,
)
from gelly_tpu.library.connected_components import (
    connected_components,
    connected_components_tree,
    labels_to_components,
)

CC_EDGES = [(1, 2), (1, 3), (2, 3), (1, 5), (6, 7), (8, 9)]
CC_EXPECTED = [[1, 2, 3, 5], [6, 7], [8, 9]]


def cc_stream(chunk_size=2, vertex_capacity=64):
    return edge_stream_from_edges(
        [(s, d, 1.0) for s, d in CC_EDGES],
        vertex_capacity=vertex_capacity,
        chunk_size=chunk_size,
    )


@pytest.mark.parametrize("merge", ["tree", "gather"])
@pytest.mark.parametrize("chunk_size", [2, 8])
def test_cc_parity_with_reference_fixture(merge, chunk_size):
    s = cc_stream(chunk_size=chunk_size)
    agg = connected_components(s.ctx.vertex_capacity, merge=merge)
    labels = s.aggregate(agg).result()
    assert labels_to_components(labels, s.ctx) == CC_EXPECTED


def test_cc_tree_alias():
    s = cc_stream()
    agg = connected_components_tree(s.ctx.vertex_capacity)
    labels = s.aggregate(agg).result()
    assert labels_to_components(labels, s.ctx) == CC_EXPECTED


def test_cc_emits_per_window_and_improves():
    # merge_every=1: one emission per chunk; summaries accumulate
    # (non-transient Merger, M/SummaryAggregation.java:107-119).
    s = cc_stream(chunk_size=2)
    agg = connected_components(s.ctx.vertex_capacity)
    emissions = list(s.aggregate(agg, merge_every=1))
    assert len(emissions) == 3  # 6 edges / chunk_size 2
    # First window: only (1,2),(1,3) seen.
    first = labels_to_components(emissions[0], s.ctx)
    assert first == [[1, 2, 3]]
    final = labels_to_components(emissions[-1], s.ctx)
    assert final == CC_EXPECTED


def test_cc_window_ms_time_windows():
    # Event-time tumbling windows: edges timestamped 0..5, window of 2 →
    # 3 windows, labels accumulate to the same final parity.
    s = edge_stream_from_edges(
        [(s_, d_, 1.0) for s_, d_ in CC_EDGES],
        vertex_capacity=64,
        chunk_size=3,
    )
    agg = connected_components(s.ctx.vertex_capacity)
    emissions = list(s.aggregate(agg, window_ms=2))
    assert labels_to_components(emissions[-1], s.ctx) == CC_EXPECTED
    assert len(emissions) == 3


def test_transient_aggregation_resets_per_window():
    # A transient count-edges aggregation: per-window counts don't accumulate.
    def init():
        return jnp.zeros((), jnp.int32)

    agg = SummaryAggregation(
        init=init,
        fold=lambda s, c: s + c.num_valid().astype(jnp.int32),
        combine=lambda a, b: a + b,
        transient=True,
    )
    s = cc_stream(chunk_size=2)
    counts = [int(x) for x in s.aggregate(agg, merge_every=1)]
    assert counts == [2, 2, 2]
    # Non-transient accumulates.
    agg2 = SummaryAggregation(
        init=init,
        fold=lambda s, c: s + c.num_valid().astype(jnp.int32),
        combine=lambda a, b: a + b,
        transient=False,
    )
    s = cc_stream(chunk_size=2)
    counts = [int(x) for x in s.aggregate(agg2, merge_every=1)]
    assert counts == [2, 4, 6]


def count_agg():
    return SummaryAggregation(
        init=lambda: jnp.zeros((), jnp.int64),
        fold=lambda s, c: s + c.num_valid().astype(jnp.int64),
        combine=lambda a, b: a + b,
    )


def test_window_gaps_do_not_fire_empty_windows():
    # Timestamps jump 0 -> 1000: no per-empty-window emissions, just 2.
    from gelly_tpu import TimeCharacteristic
    s = edge_stream_from_edges(
        [(1, 2, 1.0), (3, 4, 1.0)], vertex_capacity=16, chunk_size=2,
        time=TimeCharacteristic.EVENT, timestamps=np.array([0, 1000]),
    )
    emissions = list(s.aggregate(count_agg(), window_ms=1))
    assert [int(e) for e in emissions] == [1, 2]


def test_late_edges_counted_and_dropped():
    from gelly_tpu import TimeCharacteristic
    # Second chunk carries an edge for an already-closed window (ts=0 after
    # window 5 opened): dropped, counted in stats.
    s = edge_stream_from_edges(
        [(1, 2, 1.0), (3, 4, 1.0), (5, 6, 1.0), (7, 8, 1.0)],
        vertex_capacity=16, chunk_size=2,
        time=TimeCharacteristic.EVENT,
        timestamps=np.array([10, 11, 0, 13]),
    )
    ss = s.aggregate(count_agg(), window_ms=2)
    emissions = [int(e) for e in ss]
    assert ss.stats["late_edges"] == 1
    assert emissions[-1] == 3  # late edge never counted


def test_checkpoint_midwindow_chunk_boundary_resume(tmp_path):
    # A chunk spanning two windows: checkpoint at the chunk boundary must
    # capture the open window's edges (in locals) so resume loses nothing
    # and double-counts nothing.
    from gelly_tpu import TimeCharacteristic

    p = str(tmp_path / "w.npz")
    edges = [(1, 2, 1.0), (3, 4, 1.0), (5, 6, 1.0), (7, 8, 1.0)]
    ts = np.array([0, 1, 2, 3])

    def make(k):
        return edge_stream_from_edges(
            edges[:k], vertex_capacity=16, chunk_size=2,
            time=TimeCharacteristic.EVENT, timestamps=ts[:k],
        )

    # Run only the first chunk (ts 0,1 -> window 0 closed at ts=2? no:
    # chunk1 = ts[0,1], both window 0, stays open) with checkpointing.
    list(make(2).aggregate(count_agg(), window_ms=2, checkpoint_path=p))
    # Resume over the full stream; final total must be exactly 4.
    ss = make(4).aggregate(count_agg(), window_ms=2, checkpoint_path=p,
                           resume=True)
    emissions = [int(e) for e in ss]
    assert emissions[-1] == 4


def test_edges_fold_adapter_per_edge_udf():
    # Per-edge EdgesFold parity: sum of edge values via sequential scan.
    def fold_edges(acc, src, dst, val):
        return acc + val

    agg = SummaryAggregation(
        init=lambda: jnp.zeros((), jnp.float32),
        fold=edges_fold_adapter(fold_edges),
        combine=lambda a, b: a + b,
    )
    s = edge_stream_from_edges(
        [(1, 2, 1.5), (2, 3, 2.5), (3, 4, 3.0)], vertex_capacity=16,
        chunk_size=2,
    )
    total = float(s.aggregate(agg).result())
    assert total == pytest.approx(7.0)


def test_allowed_lateness_reorders_within_bound():
    # VERDICT r2 item 9: timestamps shuffled within lateness L must give
    # the same per-window results as the sorted stream; edges later than L
    # are still dropped + counted.
    import jax.numpy as jnp

    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable

    rng = np.random.default_rng(23)
    n = 400
    n_v = 64
    src = rng.integers(0, n_v, n).astype(np.int64)
    dst = rng.integers(0, n_v, n).astype(np.int64)
    ts_sorted = np.sort(rng.integers(0, 4000, n)).astype(np.int64)
    # Shuffle timestamps within a bound < L by permuting inside blocks.
    L = 500
    perm = np.arange(n)
    for lo in range(0, n, 40):
        seg = perm[lo:lo + 40]
        rng.shuffle(seg)
        perm[lo:lo + 40] = seg
    # Shuffle EDGES (src/dst/ts together) so arrival order is out of ts
    # order within each block but every edge keeps its own timestamp.
    def stream(order):
        return edge_stream_from_source(
            EdgeChunkSource(src[order], dst[order],
                            timestamps=ts_sorted[order], chunk_size=32,
                            table=IdentityVertexTable(n_v),
                            time=TimeCharacteristic.EVENT),
            n_v,
        )

    def collect(snap):
        out = {}
        for upd in snap.reduce_on_edges(lambda a, b: a + b):
            ok = np.asarray(upd.valid).astype(bool)
            out[upd.window] = dict(
                zip(np.asarray(upd.slots)[ok].tolist(),
                    np.asarray(upd.values)[ok].tolist())
            )
        return out

    want = collect(stream(np.arange(n)).slice(1000, "out",
                                              window_capacity=2 * n))
    # Sorted edges arrive in ts order; with lateness the shuffled stream
    # must land every edge in its true window -> identical window sums.
    snap = stream(perm).slice(1000, "out", window_capacity=2 * n,
                              allowed_lateness=2 * L)
    got = collect(snap)
    assert got == want
    assert snap.stats["late_edges"] == 0

    # Without lateness the shuffled stream drops stragglers.
    snap0 = stream(perm).slice(1000, "out", window_capacity=2 * n)
    collect(snap0)
    assert snap0.stats["late_edges"] > 0

    # An edge later than the bound is dropped + counted with lateness on.
    order_bad = np.concatenate([np.arange(1, n), [0]])  # ts~0 arrives last
    snap_bad = stream(order_bad).slice(1000, "out", window_capacity=2 * n,
                                       allowed_lateness=200)
    collect(snap_bad)
    assert snap_bad.stats["late_edges"] >= 1


def test_lateness_buffer_stats_exposed():
    # ADVICE r3: the reorder buffer's live footprint is observable —
    # buffered_edges returns to 0 after the final flush and open_windows
    # stays within the lateness/window bound while iterating.
    from gelly_tpu.core.chunk import make_chunk
    from gelly_tpu.core.windows import tumbling_window_events

    rng = np.random.default_rng(5)
    n = 256
    ts = np.sort(rng.integers(0, 4000, n)).astype(np.int64)
    chunks = [
        make_chunk(
            np.arange(32, dtype=np.int64), np.arange(32, dtype=np.int64),
            ts=ts[lo:lo + 32], capacity=32, device=False,
        )
        for lo in range(0, n, 32)
    ]
    stats: dict = {}
    seen_buffered = 0
    seen_open = 0
    # Bound: (lateness + max chunk ts span) / window_ms + 1 open windows.
    span = max(int(ts[lo:lo + 32].max() - ts[lo:lo + 32].min())
               for lo in range(0, n, 32))
    bound = -(-(500 + span) // 250) + 1
    for kind, w, c, k in tumbling_window_events(
        iter(chunks), 250, stats, allowed_lateness=500
    ):
        seen_buffered = max(seen_buffered, stats["buffered_edges"])
        seen_open = max(seen_open, stats["open_windows"])
        assert stats["open_windows"] <= bound
    assert seen_buffered > 0 and seen_open > 0
    # Fully drained after the final flush.
    assert stats["buffered_edges"] == 0
    assert stats["open_windows"] == 0


def test_allowed_lateness_engine_window_mode():
    # Engine window_ms path with lateness: CC labels equal the sorted run.
    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.library.connected_components import connected_components

    rng = np.random.default_rng(29)
    n = 300
    n_v = 64
    src = rng.integers(0, n_v, n).astype(np.int64)
    dst = rng.integers(0, n_v, n).astype(np.int64)
    ts = np.sort(rng.integers(0, 3000, n)).astype(np.int64)
    perm = np.arange(n)
    for lo in range(0, n, 30):
        seg = perm[lo:lo + 30]
        rng.shuffle(seg)
        perm[lo:lo + 30] = seg

    def run(order, lateness):
        s = edge_stream_from_source(
            EdgeChunkSource(src[order], dst[order], timestamps=ts[order],
                            chunk_size=32, table=IdentityVertexTable(n_v),
                            time=TimeCharacteristic.EVENT),
            n_v,
        )
        agg = connected_components(n_v, merge="gather",
                                   ingest_combine=False)
        outs = list(s.aggregate(agg, window_ms=1000,
                                allowed_lateness=lateness))
        return [np.asarray(o) for o in outs]

    sorted_runs = run(np.arange(n), 0)
    shuffled_runs = run(perm, 1000)
    # Same number of windows, same final labels.
    assert len(sorted_runs) == len(shuffled_runs)
    np.testing.assert_array_equal(sorted_runs[-1], shuffled_runs[-1])


def test_allowed_lateness_sorted_stream_unaffected():
    # Regression: a chunk spanning more than the lateness bound must not
    # drop its own earlier edges — on a sorted stream, lateness>0 must be
    # a no-op (same windows, zero late edges) even when one chunk covers
    # many windows.
    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable

    n, n_v = 256, 16
    rng = np.random.default_rng(31)
    src = rng.integers(0, n_v, n).astype(np.int64)
    dst = rng.integers(0, n_v, n).astype(np.int64)
    ts = np.arange(n, dtype=np.int64) * 16  # 0..4080: chunk spans ~3200ms

    def collect(L):
        s = edge_stream_from_source(
            EdgeChunkSource(src, dst, timestamps=ts, chunk_size=200,
                            table=IdentityVertexTable(n_v),
                            time=TimeCharacteristic.EVENT),
            n_v,
        )
        snap = s.slice(100, "out", window_capacity=2 * n,
                       allowed_lateness=L)
        out = {}
        for upd in snap.reduce_on_edges(lambda a, b: a + b):
            ok = np.asarray(upd.valid).astype(bool)
            out[upd.window] = dict(
                zip(np.asarray(upd.slots)[ok].tolist(),
                    np.asarray(upd.values)[ok].tolist())
            )
        return out, snap.stats["late_edges"]

    want, late0 = collect(0)
    got, late = collect(50)  # bound << chunk ts span
    assert late0 == 0 and late == 0
    assert got == want


def test_allowed_lateness_checkpoint_resume_no_drops(tmp_path):
    # VERDICT r3 item 9: allowed_lateness + checkpoint_path compose — the
    # reorder buffer is serialized to a sidecar, so a resume mid-stream
    # with IN-FLIGHT late edges drops nothing (Flink snapshots in-flight
    # window state; M/SummaryAggregation.java:121-135 parity).
    import jax.numpy as jnp

    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.engine.aggregation import SummaryAggregation

    n_v = 16
    # Timestamps shuffled within the lateness bound so edges from window
    # w arrive AFTER window w+1 opens — the checkpoint below lands while
    # those edges sit in the reorder buffer.
    ts = np.array([0, 5, 12, 3, 8, 17, 14, 9, 23, 21, 16, 27, 26, 31, 29,
                   35], np.int64)
    src = np.arange(16, dtype=np.int64) % n_v
    dst = (np.arange(16, dtype=np.int64) + 1) % n_v

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, timestamps=ts, chunk_size=4,
                            table=IdentityVertexTable(n_v),
                            time=TimeCharacteristic.EVENT),
            n_v,
        )

    def count_agg():
        return SummaryAggregation(
            init=lambda: jnp.zeros((), jnp.int64),
            fold=lambda s, c: s + jnp.sum(c.valid.astype(jnp.int64)),
            combine=lambda a, b: a + b,
        )

    kw = dict(window_ms=10, allowed_lateness=10, checkpoint_every=1)
    want = stream().aggregate(count_agg(), **kw).result()

    p = str(tmp_path / "lat.npz")
    # Partial run: stop after two emissions (checkpoints fire at the next
    # chunk boundary after a close), with later-window edges already
    # consumed into the reorder buffer.
    it = iter(stream().aggregate(count_agg(), checkpoint_path=p, **kw))
    next(it)
    next(it)
    del it
    import glob

    # Sidecars are position-stamped (crash-atomic pair: the stamp ties
    # each sidecar to the main-file position it belongs to).
    assert glob.glob(p + ".lateness.*")
    got = stream().aggregate(
        count_agg(), checkpoint_path=p, resume=True, **kw
    ).result()
    # Total folded edges must equal the uninterrupted run's (no buffered
    # edge lost, none double-counted).
    assert int(got) == int(want) == 16


def test_lateness_sidecar_crash_between_writes_recovers(tmp_path):
    """A crash AFTER the new sidecar write but BEFORE the main-file
    os.replace must leave the old (consistent) pair restorable — the
    position-stamped sidecar names guarantee the main file's matching
    sidecar is never overwritten in that window."""
    import glob
    import os
    import shutil

    from gelly_tpu.core.io import EdgeChunkSource, TimeCharacteristic
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable

    n_v = 8
    ts = np.array([0, 5, 12, 3, 8, 17, 14, 9, 23, 21, 16, 27, 26, 31, 29,
                   35], np.int64)
    src = np.arange(16, dtype=np.int64) % n_v
    dst = (np.arange(16, dtype=np.int64) + 1) % n_v

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, timestamps=ts, chunk_size=4,
                            table=IdentityVertexTable(n_v),
                            time=TimeCharacteristic.EVENT),
            n_v,
        )

    def count_agg():
        return SummaryAggregation(
            init=lambda: jnp.zeros((), jnp.int64),
            fold=lambda s, c: s + jnp.sum(c.valid.astype(jnp.int64)),
            combine=lambda a, b: a + b,
        )

    kw = dict(window_ms=10, allowed_lateness=10, checkpoint_every=1)
    want = stream().aggregate(count_agg(), **kw).result()

    p = str(tmp_path / "lat.npz")
    it = iter(stream().aggregate(count_agg(), checkpoint_path=p, **kw))
    next(it)
    next(it)
    del it
    sides = glob.glob(p + ".lateness.*")
    assert sides
    # Simulate the crash window: a NEWER-position sidecar landed on disk
    # but the main checkpoint never advanced.
    pos = int(sides[0].rsplit(".", 1)[1])
    shutil.copy(sides[0], f"{p}.lateness.{pos + 3}")
    got = stream().aggregate(
        count_agg(), checkpoint_path=p, resume=True, **kw
    ).result()
    assert int(got) == int(want) == 16
    # A completed post-resume checkpoint prunes every stale sidecar.
    leftover = glob.glob(p + ".lateness.*")
    assert len(leftover) <= 1
    if leftover:
        assert not os.path.exists(f"{p}.lateness.{pos}") or \
            leftover[0] != f"{p}.lateness.{pos}"


def test_allowed_lateness_requires_window_mode():
    from gelly_tpu.library.connected_components import connected_components

    s = cc_stream()
    agg = connected_components(s.ctx.vertex_capacity, ingest_combine=False)
    with pytest.raises(ValueError, match="allowed_lateness"):
        s.aggregate(agg, merge_every=2, allowed_lateness=10).result()


def test_raw_dedup_fold_pipeline_parity(monkeypatch):
    """The large-chunk raw fold path (union_edges_dedup, VERDICT r4
    item 4) must produce identical labels through the FULL engine
    pipeline. Chunks in tests are small, so the selection threshold is
    lowered to force the dedup path, and the result is compared against
    the generic-kernel run."""
    import importlib

    ccmod = importlib.import_module(
        "gelly_tpu.library.connected_components"
    )
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.parallel import mesh as mesh_lib

    n_v = 512
    rng = np.random.default_rng(41)
    src = (rng.zipf(1.4, 3000) % n_v).astype(np.int64)
    dst = (rng.zipf(1.4, 3000) % n_v).astype(np.int64)

    def stream():
        return edge_stream_from_source(
            EdgeChunkSource(src, dst, chunk_size=256,
                            table=IdentityVertexTable(n_v)),
            n_v,
        )

    m1 = mesh_lib.make_mesh(1)

    def run():
        agg = ccmod.connected_components(n_v, ingest_combine=False)
        return np.asarray(
            stream().aggregate(agg, mesh=m1, merge_every=4).result()
        )

    generic = run()
    monkeypatch.setattr(ccmod, "RAW_DEDUP_MIN_CHUNK", 64)
    dedup = run()
    assert np.array_equal(generic, dedup)
