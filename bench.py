"""Benchmark: streaming Connected Components edges/sec (north-star config).

Runs the BASELINE.json north-star workload — streaming CC over a synthetic
power-law edge stream — on the available accelerator, and measures the CPU
baseline in-process (the reference publishes no numbers, BASELINE.md: the
baseline must be measured, not quoted). The baseline is a faithful
re-implementation of the reference's per-edge fold semantics in host Python:
``DisjointSet.union`` with path compression per edge
(``/root/reference/src/main/java/org/apache/flink/graph/streaming/summaries/DisjointSet.java:66-118``),
folded edge-by-edge as ``UpdateCC`` does
(``.../library/ConnectedComponents.java:82-87``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def synth_edges(num_edges: int, num_vertices: int, seed: int = 7):
    """Power-law-ish edge stream (Zipf endpoints, the skew CC cares about)."""
    rng = np.random.default_rng(seed)
    # Zipf over a permuted id space so hot vertices are spread across slots.
    a = 1.3
    src = rng.zipf(a, size=num_edges) % num_vertices
    dst = rng.zipf(a, size=num_edges) % num_vertices
    perm = rng.permutation(num_vertices)
    return perm[src].astype(np.int64), perm[dst].astype(np.int64)


def baseline_cc(src: np.ndarray, dst: np.ndarray) -> tuple[dict, float]:
    """Reference-semantics per-edge union-find fold on host CPU."""
    parent: dict[int, int] = {}

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:  # path compression
            parent[x], x = root, parent[x]
        return root

    t0 = time.perf_counter()
    for u, v in zip(src.tolist(), dst.tolist()):
        if u not in parent:
            parent[u] = u
        if v not in parent:
            parent[v] = v
        ru, rv = find(u), find(v)
        if ru != rv:
            if ru < rv:
                parent[rv] = ru
            else:
                parent[ru] = rv
    dt = time.perf_counter() - t0
    labels = {x: find(x) for x in parent}
    return labels, dt


def tpu_cc(src, dst, num_vertices: int, chunk_size: int, merge_every: int):
    import jax

    from gelly_tpu import edge_stream_from_edges  # noqa: F401  (registers x64)
    from gelly_tpu.core.io import EdgeChunkSource
    from gelly_tpu.core.stream import edge_stream_from_source
    from gelly_tpu.core.vertices import IdentityVertexTable
    from gelly_tpu.library.connected_components import connected_components

    def make_stream():
        # Ids are already dense in [0, num_vertices): the identity table is
        # the documented fast path, keeping hash densification out of the
        # measured region.
        srcq = EdgeChunkSource(src, dst, chunk_size=chunk_size,
                               table=IdentityVertexTable(num_vertices))
        return edge_stream_from_source(srcq, num_vertices)

    agg = connected_components(num_vertices, merge="gather")

    # Warmup: compile fold/merge on a tiny prefix.
    warm = EdgeChunkSource(src[: chunk_size * 2], dst[: chunk_size * 2],
                           chunk_size=chunk_size,
                           table=IdentityVertexTable(num_vertices))
    warm_stream = edge_stream_from_source(warm, num_vertices)
    warm_stream.aggregate(agg, merge_every=merge_every).result()

    stream = make_stream()
    t0 = time.perf_counter()
    labels = stream.aggregate(agg, merge_every=merge_every).result()
    jax.block_until_ready(labels)
    dt = time.perf_counter() - t0
    return labels, stream.ctx, dt


def components_of(labels_by_id: dict) -> set[frozenset]:
    comps: dict[int, set] = {}
    for v, lbl in labels_by_id.items():
        comps.setdefault(lbl, set()).add(v)
    return {frozenset(c) for c in comps.values()}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--edges", type=int, default=2_000_000)
    p.add_argument("--vertices", type=int, default=1 << 17)
    p.add_argument("--chunk-size", type=int, default=1 << 17)
    p.add_argument("--merge-every", type=int, default=4)
    p.add_argument("--skip-parity", action="store_true")
    args = p.parse_args()

    src, dst = synth_edges(args.edges, args.vertices)

    labels, ctx, dt_tpu = tpu_cc(
        src, dst, args.vertices, args.chunk_size, args.merge_every
    )
    eps = args.edges / dt_tpu

    base_labels, dt_base = baseline_cc(src, dst)
    base_eps = args.edges / dt_base

    if not args.skip_parity:
        lab = np.asarray(labels)
        slots = np.nonzero(lab >= 0)[0]
        raw = ctx.decode(slots)
        ours = components_of(
            {int(r): int(lab[s]) for s, r in zip(slots, raw)}
        )
        theirs = components_of(base_labels)
        if ours != theirs:
            print(
                json.dumps({"error": "label parity FAILED",
                            "ours": len(ours), "theirs": len(theirs)}),
                file=sys.stderr,
            )
            return 1

    print(json.dumps({
        "metric": "streaming_cc_throughput",
        "value": round(eps, 1),
        "unit": "edges/sec",
        "vs_baseline": round(eps / base_eps, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
